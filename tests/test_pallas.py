"""Pallas stencil kernel tests.

Interpret-mode tier runs on any platform (kernel semantics vs the jnp
step — SURVEY.md §4 'Pallas stencil kernel ≡ jnp step'). Compiled tier
runs only when a real TPU is visible.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heat3d_tpu.core.config import (
    GridConfig,
    MeshConfig,
    Precision,
    SolverConfig,
    StencilConfig,
)
from heat3d_tpu.core.stencils import STENCILS, stencil_taps
from heat3d_tpu.ops.stencil_jnp import apply_taps_padded
from heat3d_tpu.utils.compat import shard_map
from heat3d_tpu.ops.stencil_pallas import (
    apply_taps_pallas,
    choose_blocks,
    pallas_supported,
)

ON_TPU = jax.devices()[0].platform == "tpu"


def _taps(kind):
    return stencil_taps(STENCILS[kind], 1.0, 0.05, (1.0, 1.0, 1.0))


def _padded(shape, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.standard_normal(tuple(s + 2 for s in shape)).astype(dtype)
    )


@pytest.mark.parametrize("kind", ["7pt", "27pt"])
@pytest.mark.parametrize("shape", [(8, 8, 8), (16, 32, 24), (24, 8, 40)])
def test_interpret_matches_jnp(kind, shape):
    up = _padded(shape, seed=1)
    want = apply_taps_padded(up, _taps(kind))
    got = apply_taps_pallas(up, _taps(kind), interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_interpret_bf16_storage_fp32_compute():
    up = _padded((8, 16, 16), seed=2).astype(jnp.bfloat16)
    want = apply_taps_padded(
        up, _taps("7pt"), compute_dtype=jnp.float32, out_dtype=jnp.bfloat16
    )
    got = apply_taps_pallas(
        up, _taps("7pt"), compute_dtype=jnp.float32, out_dtype=jnp.bfloat16,
        interpret=True,
    )
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32),
        np.asarray(want, dtype=np.float32),
        rtol=1e-2, atol=1e-2,
    )


@pytest.mark.parametrize("kind", ["7pt", "27pt"])
@pytest.mark.parametrize(
    "bc,bcv", [("dirichlet", 0.0), ("dirichlet", 1.5), ("periodic", 0.0)]
)
def test_stream2_interpret_matches_unfused(kind, bc, bcv):
    """Fused two-update kernel == two single applications with mid-ghost
    pinning, on a (1,1,1) mesh (every boundary is a domain edge)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from heat3d_tpu.core.config import BoundaryCondition
    from heat3d_tpu.ops.stencil_pallas import apply_taps_pallas_stream2
    from heat3d_tpu.parallel.step import exchange, _local_stepk
    from heat3d_tpu.parallel.topology import build_mesh

    bce = BoundaryCondition(bc)
    cfg = SolverConfig(
        grid=GridConfig.cube(8),
        stencil=StencilConfig(kind=kind, bc=bce, bc_value=bcv),
        mesh=MeshConfig(shape=(1, 1, 1)),
        backend="jnp",
        time_blocking=2,
    )
    taps = _taps(kind)
    mesh = build_mesh(cfg.mesh)
    u = jnp.asarray(np.random.default_rng(9).standard_normal((8, 8, 8)).astype(np.float32))
    spec = P("x", "y", "z")

    want = shard_map(
        lambda x: _local_stepk(x, taps, cfg, apply_taps_padded),
        mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False,
    )(u)

    def fused(x):
        up2 = exchange(x, cfg, width=2)
        return apply_taps_pallas_stream2(
            up2, taps, ("x", "y", "z"),
            periodic=bce is BoundaryCondition.PERIODIC,
            bc_value=bcv, interpret=True,
        )

    got = shard_map(
        fused, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )(u)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


@pytest.mark.parametrize("kind", ["7pt", "27pt"])
@pytest.mark.parametrize(
    "bc,bcv", [("dirichlet", 0.0), ("dirichlet", 1.5), ("periodic", 0.0)]
)
@pytest.mark.parametrize("k", [3, 4])
def test_streamk_interpret_matches_unfused(kind, bc, bcv, k):
    """Fused k-sweep kernel == k single applications with shrinking
    mid-ghost pinning, on a (1,1,1) mesh (every boundary a domain edge).
    The deep-tb generalization of the stream2 contract."""
    from jax.sharding import PartitionSpec as P

    from heat3d_tpu.core.config import BoundaryCondition
    from heat3d_tpu.ops.stencil_pallas import apply_taps_pallas_streamk
    from heat3d_tpu.parallel.step import _local_stepk, exchange
    from heat3d_tpu.parallel.topology import build_mesh

    bce = BoundaryCondition(bc)
    cfg = SolverConfig(
        grid=GridConfig.cube(8),
        stencil=StencilConfig(kind=kind, bc=bce, bc_value=bcv),
        mesh=MeshConfig(shape=(1, 1, 1)),
        backend="jnp",
        time_blocking=k,
    )
    taps = _taps(kind)
    mesh = build_mesh(cfg.mesh)
    u = jnp.asarray(
        np.random.default_rng(11).standard_normal((8, 8, 8)).astype(np.float32)
    )
    spec = P("x", "y", "z")

    want = shard_map(
        lambda x: _local_stepk(x, taps, cfg, apply_taps_padded),
        mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False,
    )(u)

    def fused(x):
        upk = exchange(x, cfg, width=k)
        return apply_taps_pallas_streamk(
            upk, taps, k, ("x", "y", "z"),
            periodic=bce is BoundaryCondition.PERIODIC,
            bc_value=bcv, interpret=True,
        )

    got = shard_map(
        fused, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )(u)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_streamk_route_stands_down_off_tpu(monkeypatch):
    """The fused k-sweep route resolves ONLY on TPU (or under the
    interpret env): off-TPU the resolver returns None and the superstep
    runs the jnp ring recompute — the dispatch contract of ISSUE 5."""
    from heat3d_tpu.parallel.step import _fused_streamk_fn

    monkeypatch.delenv("HEAT3D_DIRECT_INTERPRET", raising=False)
    monkeypatch.delenv("HEAT3D_DIRECT_FORCE", raising=False)
    cfg = SolverConfig(
        grid=GridConfig.cube(16), mesh=MeshConfig(shape=(1, 1, 1)),
        backend="auto", time_blocking=3,
    )
    fn = _fused_streamk_fn(cfg)
    if ON_TPU:
        assert fn is not None
    else:
        assert fn is None
    # tb outside the fused scope (k=2..4) never resolves, anywhere
    import dataclasses

    assert _fused_streamk_fn(dataclasses.replace(cfg, time_blocking=5)) is None
    # overlap routes through the fused-DMA branch / mutual exclusion, so
    # the streamk resolver must stand down for it
    assert (
        _fused_streamk_fn(dataclasses.replace(cfg, overlap=True)) is None
    )
    # jnp backend pins the exchange path (shared _kernel_env_gate rule)
    assert _fused_streamk_fn(dataclasses.replace(cfg, backend="jnp")) is None


@pytest.mark.parametrize("k", [2, 3, 4])
def test_streamk_superstep_route_interpret_end_to_end(monkeypatch, k):
    """With the interpret env the production make_superstep_fn dispatch
    selects the streamk kernel, and the full fixed-step loop (supersteps
    + remainder steps) matches the plain per-step loop."""
    import dataclasses

    from heat3d_tpu.core import golden
    from heat3d_tpu.parallel.step import make_multistep_fn
    from heat3d_tpu.parallel.topology import build_mesh

    monkeypatch.setenv("HEAT3D_DIRECT_INTERPRET", "1")
    monkeypatch.setenv("HEAT3D_NO_DIRECT", "1")  # pin the streamk route
    cfg = SolverConfig(
        grid=GridConfig.cube(8), mesh=MeshConfig(shape=(1, 1, 1)),
        backend="auto", time_blocking=k,
    )
    from heat3d_tpu.parallel.step import _fused_streamk_fn

    assert _fused_streamk_fn(cfg) is not None  # interpret tier resolves
    mesh = build_mesh(cfg.mesh)
    u = jnp.asarray(golden.random_init((8, 8, 8), seed=21))
    got = jax.jit(make_multistep_fn(cfg, mesh))(u, jnp.int32(k + 1))
    cfg1 = dataclasses.replace(cfg, time_blocking=1, backend="jnp")
    want = jax.jit(make_multistep_fn(cfg1, mesh))(u, jnp.int32(k + 1))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU")
@pytest.mark.tpu_smoke
@pytest.mark.parametrize("k", [3, 4])
def test_streamk_compiled_on_tpu(k):
    """Fused k-sweep kernel compiles and matches k jnp steps on hardware
    (the deep-tb bench path)."""
    import dataclasses

    from heat3d_tpu.core import golden
    from heat3d_tpu.models.heat3d import HeatSolver3D

    cfg = SolverConfig(
        grid=GridConfig.cube(64), mesh=MeshConfig(shape=(1, 1, 1)),
        backend="pallas", time_blocking=k,
    )
    cfg1 = dataclasses.replace(cfg, time_blocking=1, backend="jnp")
    u_host = golden.random_init((64, 64, 64), seed=13)
    sk = HeatSolver3D(cfg)
    s1 = HeatSolver3D(cfg1)
    got = sk.gather(sk.run(sk.init_state(u_host), 2 * k))
    want = s1.gather(s1.run(s1.init_state(u_host), 2 * k))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU")
@pytest.mark.tpu_smoke
def test_stream2_compiled_on_tpu():
    """Fused two-update kernel compiles and matches two jnp steps on
    hardware (the temporally-blocked bench path)."""
    import dataclasses

    from heat3d_tpu.core import golden
    from heat3d_tpu.models.heat3d import HeatSolver3D

    cfg = SolverConfig(
        grid=GridConfig.cube(64), mesh=MeshConfig(shape=(1, 1, 1)),
        backend="pallas", time_blocking=2,
    )
    cfg1 = dataclasses.replace(cfg, time_blocking=1, backend="jnp")
    u_host = golden.random_init((64, 64, 64), seed=12)
    s2 = HeatSolver3D(cfg)
    s1 = HeatSolver3D(cfg1)
    got = s2.gather(s2.run(s2.init_state(u_host), 6))
    want = s1.gather(s1.run(s1.init_state(u_host), 6))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_choose_blocks_divides_and_fits():
    for shape in [(8, 8, 8), (128, 128, 128), (64, 256, 512), (512, 64, 1024)]:
        blocks = choose_blocks(shape)
        assert blocks is not None, shape
        bx, by = blocks
        assert shape[0] % bx == 0 and shape[1] % by == 0


def test_pallas_supported_gating():
    cfg = SolverConfig(
        grid=GridConfig.cube(16), mesh=MeshConfig(shape=(1, 1, 1)),
        backend="pallas",
    )
    ok, why = pallas_supported(cfg)
    if ON_TPU:
        assert ok, why
    else:
        assert not ok and "platform" in why


@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU")
@pytest.mark.tpu_smoke
@pytest.mark.parametrize("kind", ["7pt", "27pt"])
def test_compiled_matches_jnp_on_tpu(kind):
    up = _padded((16, 32, 128), seed=3)
    want = apply_taps_padded(up, _taps(kind))
    got = apply_taps_pallas(up, _taps(kind))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU")
def test_compiled_bf16_on_tpu():
    up = _padded((16, 32, 128), seed=4).astype(jnp.bfloat16)
    want = apply_taps_padded(
        up, _taps("7pt"), compute_dtype=jnp.float32, out_dtype=jnp.bfloat16
    )
    got = apply_taps_pallas(
        up, _taps("7pt"), compute_dtype=jnp.float32, out_dtype=jnp.bfloat16
    )
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32),
        np.asarray(want, dtype=np.float32),
        rtol=1e-2, atol=1e-2,
    )


@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU")
def test_overlap_with_pallas_backend_on_tpu(monkeypatch):
    """overlap=True feeds the Pallas kernel an odd-extent (n-2)^3 interior —
    must compile (full-extent y window, literal-0 offset) and match.
    HEAT3D_NO_DIRECT pins the windowed interior/boundary split: by default
    overlap now rides the direct kernel, which would bypass this path."""
    import dataclasses

    monkeypatch.setenv("HEAT3D_NO_DIRECT", "1")

    from heat3d_tpu.core import golden
    from heat3d_tpu.models.heat3d import HeatSolver3D

    cfg = SolverConfig(
        grid=GridConfig.cube(32), mesh=MeshConfig(shape=(1, 1, 1)),
        backend="pallas",
    )
    u0 = jnp.asarray(golden.random_init((32, 32, 32), seed=5))
    a = HeatSolver3D(cfg)
    b = HeatSolver3D(dataclasses.replace(cfg, overlap=True))
    np.testing.assert_allclose(
        np.asarray(a.step(jnp.array(u0))),
        np.asarray(b.step(jnp.array(u0))),
        rtol=1e-6, atol=1e-6,
    )


@pytest.mark.skipif(not ON_TPU, reason="needs a real TPU")
def test_solver_pallas_backend_end_to_end():
    from heat3d_tpu.core import golden
    from heat3d_tpu.models.heat3d import HeatSolver3D

    cfg = SolverConfig(
        grid=GridConfig.cube(32), mesh=MeshConfig(shape=(1, 1, 1)),
        backend="pallas",
    )
    solver = HeatSolver3D(cfg)
    u = solver.init_state("gaussian")
    u = solver.run(u, 5)
    want = golden.run(
        golden.gaussian_init(cfg.grid.shape).astype(np.float64),
        cfg.grid, cfg.stencil, 5,
    )
    np.testing.assert_allclose(solver.gather(u), want, rtol=1e-4, atol=1e-5)
