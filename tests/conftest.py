"""Shared fixtures. Test strategy per SURVEY.md §4: NumPy golden oracle,
single-device jnp vs golden, distributed (1,1,1)-mesh vs single-device,
real 8-device CPU-mesh subprocess checks (test_multidevice.py), and
compile-only lowering for larger multi-chip meshes (SURVEY.md §7.0).
"""

import os
import sys

# Allow running from a source checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)


def small_field(shape=(8, 8, 8), seed=0):
    return np.random.default_rng(seed).standard_normal(shape).astype(np.float32)


@pytest.fixture
def field8():
    return small_field((8, 8, 8))


FP32_TOL = 1e-5  # relative, single step
