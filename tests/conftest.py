"""Shared fixtures. Test strategy per SURVEY.md §4: NumPy golden oracle,
single-device jnp vs golden, distributed (1,1,1)-mesh vs single-device,
real 8-device CPU-mesh subprocess checks (test_multidevice.py), and
compile-only lowering for larger multi-chip meshes (SURVEY.md §7.0).
"""

import os
import sys

# Allow running from a source checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest

# The measurement scripts time-bound the on-chip smoke tier with coreutils
# `timeout` (SIGTERM); a test session killed mid-claim must release the
# axon pool's chip claim on the way out. backendprobe.install_sigterm_exit
# is the WRONG layer here: its SystemExit would be raised inside whatever
# test frame is executing, where pytest catches it as that one test's
# failure and keeps running — claim still held. pytest.exit() ends the
# whole session (teardown + atexit -> PJRT cleanup). A handler can still
# only fire between Python bytecodes, so the scripts pair their `timeout`
# with `-k <grace>` as the SIGKILL backstop for C-stuck sessions.


def _sigterm_ends_session(signum, frame):
    pytest.exit("SIGTERM — releasing backend and ending session", returncode=3)


import signal  # noqa: E402
import threading  # noqa: E402

if threading.current_thread() is threading.main_thread():
    signal.signal(signal.SIGTERM, _sigterm_ends_session)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)


def small_field(shape=(8, 8, 8), seed=0):
    return np.random.default_rng(seed).standard_normal(shape).astype(np.float32)


@pytest.fixture
def field8():
    return small_field((8, 8, 8))


FP32_TOL = 1e-5  # relative, single step


def abstract_lowering_supported() -> bool:
    """Whether this jax can compile-only-lower over an AbstractMesh — the
    distributed-without-cluster validation tier (SURVEY.md §4, §7.0).
    jax 0.4.x constructs the AbstractMesh (utils.compat shims the
    constructor) but its jit lowering dies with ``_device_assignment is
    not implemented for AbstractMesh``; the lowering tests skip-gate on
    this probe instead of failing 20+ times with the same version gap."""
    global _ABSTRACT_LOWERING_OK
    if _ABSTRACT_LOWERING_OK is None:
        import numpy as _np

        from heat3d_tpu.core.config import MeshConfig
        from heat3d_tpu.parallel.topology import lower_for_mesh
        from jax.sharding import PartitionSpec

        try:
            lower_for_mesh(
                lambda x: x + 1,
                MeshConfig(shape=(2, 1, 1)),
                ((4, 4, 4), _np.float32, PartitionSpec("x")),
            )
            _ABSTRACT_LOWERING_OK = True
        except Exception:
            _ABSTRACT_LOWERING_OK = False
    return _ABSTRACT_LOWERING_OK


_ABSTRACT_LOWERING_OK = None
