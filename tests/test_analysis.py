"""Static-analysis subsystem tests (tier-1, CPU): every checker fires on
a seeded-violation fixture and stays quiet on compliant code, the
finding/suppression framework (fingerprints, inline ok-comments,
baseline round trip, rc policy) behaves as docs/ANALYSIS.md promises,
the promoted data-lint cores keep their scripted behavior, and — the
acceptance gate — `heat3d lint --json` is clean on this repo itself."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from heat3d_tpu.analysis import CHECKERS, astutil, collectives, failsoft
from heat3d_tpu.analysis import knobs as knobs_checker
from heat3d_tpu.analysis import ledgerlint, provenance, taxonomy, vmem
from heat3d_tpu.analysis.cli import main as lint_main
from heat3d_tpu.analysis.cli import run_checkers
from heat3d_tpu.analysis.findings import (
    ERROR,
    WARNING,
    Finding,
    apply_suppressions,
    exit_code,
    load_baseline,
    write_baseline,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _fresh_ast_cache():
    """Fixture files are rewritten across tests under tmp paths; a stale
    parse cache would cross-contaminate them."""
    astutil.clear_cache()
    yield
    astutil.clear_cache()


def _write(tmp_path, relpath, source):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return str(path)


def _codes(findings):
    return sorted(f.code for f in findings)


# ---- collective-divergence ----------------------------------------------


BAD_COLLECTIVES = """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def process_guarded(u):
        if jax.process_index() == 0:
            u = lax.ppermute(u, "x", [(0, 1)])
        return u

    def taint_guarded(u):
        pid = jax.process_index()
        if pid == 0:
            u = lax.psum(u, "x")
        return u

    def device_guarded(u):
        idx = lax.axis_index("x")
        if idx == 0:
            u = lax.psum(u, "x")
        return u

    def data_guarded(u, thresh):
        if float(jnp.max(u)) > thresh:
            u = lax.psum(u, "x")
        return u

    def wraps_collective(u):
        return lax.ppermute(u, "x", [(0, 1)])

    def indirect_guarded(u):
        if jax.process_index() == 0:
            u = wraps_collective(u)
        return u
"""

GOOD_COLLECTIVES = """
    from jax import lax

    def uniform_guard(u, periodic):
        if periodic:
            u = lax.ppermute(u, "x", [(0, 1)])
        return u

    def unguarded(u):
        return lax.psum(u, "x")

    def no_collective(u):
        if float(u.sum()) > 0:
            return u * 2
        return u
"""


def test_collective_divergence_fires_on_seeded_hazards(tmp_path):
    path = _write(tmp_path, "pkg/bad_coll.py", BAD_COLLECTIVES)
    found = collectives.check(str(tmp_path), files=[path])
    by_sym = {f.symbol: f for f in found}
    # every seeded hazard flagged, each with the right divergence class
    assert by_sym["process_guarded"].code == "ANL101"
    assert by_sym["taint_guarded"].code == "ANL101"
    assert by_sym["device_guarded"].code == "ANL102"
    assert by_sym["data_guarded"].code == "ANL103"
    # the call-graph fixpoint sees through the wrapper
    assert by_sym["indirect_guarded"].code == "ANL101"
    assert "collective-bearing" in by_sym["indirect_guarded"].message
    assert all(f.severity == ERROR for f in found)
    # the unguarded wrapper itself is not a finding
    assert "wraps_collective" not in by_sym


def test_collective_divergence_quiet_on_uniform_guards(tmp_path):
    path = _write(tmp_path, "pkg/good_coll.py", GOOD_COLLECTIVES)
    assert collectives.check(str(tmp_path), files=[path]) == []


# ---- fail-soft enforcement ----------------------------------------------


LEAKY_OBS = """
    import json

    def leaky_write(path, payload):
        with open(path, "w") as f:
            f.write(payload)

    def leaky_encode(payload):
        return json.dumps(payload)

    def guarded_write(path, payload):
        try:
            with open(path, "w") as f:
                f.write(payload)
        except OSError:
            pass

    def calls_leaky(path):
        leaky_write(path, "x")

    def guards_leaky_call(path):
        try:
            leaky_write(path, "x")
        except Exception:
            pass
"""


def test_failsoft_fires_on_leaky_and_propagated_io(tmp_path):
    relp = "obspkg/telemetry.py"
    _write(tmp_path, relp, LEAKY_OBS)
    contract = {
        relp: (
            "leaky_write",
            "leaky_encode",
            "guarded_write",
            "calls_leaky",
            "guards_leaky_call",
        )
    }
    found = failsoft.check(str(tmp_path), contract=contract)
    by_sym = {f.symbol: f for f in found}
    assert by_sym["leaky_write"].code == "ANL201"
    assert "OSError" in by_sym["leaky_write"].message
    assert "TypeError" in by_sym["leaky_encode"].message
    # risk propagates caller-ward through the intra-package call graph...
    assert by_sym["calls_leaky"].code == "ANL201"
    # ...but a guard at either layer absorbs it
    assert "guarded_write" not in by_sym
    assert "guards_leaky_call" not in by_sym


def test_failsoft_flags_contract_naming_missing_function(tmp_path):
    relp = "obspkg/telemetry.py"
    _write(tmp_path, relp, "def present():\n    pass\n")
    found = failsoft.check(
        str(tmp_path), contract={relp: ("present", "renamed_away")}
    )
    assert _codes(found) == ["ANL202"]
    assert found[0].symbol == "renamed_away"


def test_failsoft_live_obs_surface_is_clean():
    """The real contract over the real obs/ package: the PR 2 invariant,
    mechanically enforced from here on."""
    assert failsoft.check(REPO) == []


# ---- vmem-budget ---------------------------------------------------------


BAD_VMEM = """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def build(kernel, out_shape, dtype):
        return pl.pallas_call(
            kernel,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((4, 8, 128), dtype)],
        )
"""

GOOD_VMEM = """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def build(kernel, out_shape, dtype, nslots):
        return pl.pallas_call(
            kernel,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((3, 8, 128), dtype),
                pltpu.VMEM((nslots, 8, 128), dtype),  # dynamic: shape math
            ],
            cost_estimate=pl.CostEstimate(
                flops=1, bytes_accessed=1, transcendentals=0
            ),
        )
"""


def test_vmem_ast_fires_on_missing_cost_and_bad_ring(tmp_path):
    path = _write(tmp_path, "pkg/bad_kernel.py", BAD_VMEM)
    found = vmem.check(str(tmp_path), files=[path])
    assert _codes(found) == ["ANL301", "ANL302"]
    ring = next(f for f in found if f.code == "ANL302")
    assert "4 slots" in ring.message


def test_vmem_ast_quiet_on_compliant_kernel(tmp_path):
    path = _write(tmp_path, "pkg/good_kernel.py", GOOD_VMEM)
    assert vmem.check(str(tmp_path), files=[path]) == []


def test_vmem_budget_arithmetic_fires_on_tiny_chip():
    """The budget audit drives the repo's real estimators: against a
    fictional 4 MiB part every admit budget is over-ceiling."""
    found = vmem.check(
        REPO, files=[], chip_table={"tpu-tiny": 4 * vmem.MIB}
    )
    assert "ANL303" in _codes(found)
    assert all(f.severity in (ERROR, WARNING) or f.code == "ANL307" for f in found)


def test_vmem_budget_arithmetic_clean_on_real_chip_table():
    """The repo's own budgets fit every known generation (the ANL305
    fused-DMA note on 16 MiB parts is a documented warning, not an
    error)."""
    found = vmem.check(REPO, files=[], chip_table=dict(vmem.CHIP_VMEM_BYTES))
    assert [f for f in found if f.severity == ERROR] == []


# ---- ledger-taxonomy -----------------------------------------------------


BAD_TAXONOMY = """
    import os

    def run(ledger):
        ledger.event("unregistered_event")
        with ledger.span("run_loop"):
            pass
        ledger.event("warmup")  # registered as a span
        os.environ.get("HEAT3D_MYSTERY_KNOB")
"""

GOOD_TAXONOMY = """
    def run(ledger):
        ledger.event("run_start")
        with ledger.span("run_loop"):
            pass
"""

_EVENTS = {
    "run_start": {"kind": "point", "desc": "x"},
    "run_loop": {"kind": "span", "desc": "x"},
    "warmup": {"kind": "span", "desc": "x"},
    "stale_event": {"kind": "point", "desc": "x"},
}


def test_taxonomy_fires_on_drifted_vocabulary(tmp_path):
    path = _write(tmp_path, "pkg/emitters.py", BAD_TAXONOMY)
    # docs cover everything except stale_event and the mystery knob
    _write(
        tmp_path,
        "docs/OBS.md",
        "| `run_start` | point | x |\n"
        "| `run_loop` | span | x |\n"
        "| `warmup` | span | x |\n"
        "| `HEAT3D_DOCUMENTED_KNOB` | x |\n",
    )
    found = taxonomy.check(
        str(tmp_path),
        files=[path],
        events_registry=_EVENTS,
        env_registry={"HEAT3D_DOCUMENTED_KNOB": {"desc": "x"}},
        docs_path="docs/OBS.md",
    )
    codes = {f.code for f in found}
    assert codes == {
        "ANL401",  # unregistered_event emitted but not registered
        "ANL402",  # warmup emitted as point, registered as span
        "ANL403",  # stale_event / run_start registered, never emitted
        "ANL404",  # stale_event missing from the docs table
        "ANL411",  # HEAT3D_MYSTERY_KNOB referenced, unregistered
        "ANL413",  # HEAT3D_DOCUMENTED_KNOB registered, never referenced
    }
    stale = [f for f in found if f.code == "ANL403"]
    assert {f.symbol for f in stale} == {"stale_event", "run_start"}


_GOOD_DOCS = (
    "| `run_start` | point | x |\n"
    "| `run_loop` | span | x |\n"
)


def test_taxonomy_quiet_on_registered_vocabulary(tmp_path):
    path = _write(tmp_path, "pkg/emitters.py", GOOD_TAXONOMY)
    _write(tmp_path, "docs/OBS.md", _GOOD_DOCS)
    found = taxonomy.check(
        str(tmp_path),
        files=[path],
        events_registry={
            "run_start": {"kind": "point", "desc": "x"},
            "run_loop": {"kind": "span", "desc": "x"},
        },
        env_registry={},
        docs_path="docs/OBS.md",
    )
    assert found == []


def test_taxonomy_docs_check_is_row_anchored(tmp_path):
    """A deleted table row is caught even when its name is a prefix of a
    surviving row's, and a docs row whose kind column drifted from the
    registry is a finding too."""
    path = _write(tmp_path, "pkg/emitters.py", GOOD_TAXONOMY)
    registry = {
        "run_start": {"kind": "point", "desc": "x"},
        "run_loop": {"kind": "span", "desc": "x"},
        "run": {"kind": "point", "desc": "x", "external": True},
    }
    # `run`'s own row was deleted; `run_start`/`run_loop` rows contain
    # the substring "run" but must not satisfy the check
    _write(tmp_path, "docs/OBS.md", _GOOD_DOCS)
    found = taxonomy.check(
        str(tmp_path), files=[path], events_registry=registry,
        env_registry={}, docs_path="docs/OBS.md",
    )
    assert [(f.code, f.symbol) for f in found] == [("ANL404", "run")]
    # kind drift: docs say warmup is a point, registry says span
    _write(tmp_path, "docs/OBS2.md", _GOOD_DOCS + "| `warmup` | point | x |\n")
    found = taxonomy.check(
        str(tmp_path),
        files=[path],
        events_registry={
            "run_start": {"kind": "point", "desc": "x"},
            "run_loop": {"kind": "span", "desc": "x"},
            "warmup": {"kind": "span", "desc": "x", "external": True},
        },
        env_registry={},
        docs_path="docs/OBS2.md",
    )
    assert [(f.code, f.symbol) for f in found] == [("ANL404", "warmup")]


def test_taxonomy_unreadable_docs_is_a_finding(tmp_path):
    """A missing docs file must not silently disable the documentation
    leg — it is itself an error finding (ANL405)."""
    path = _write(tmp_path, "pkg/emitters.py", GOOD_TAXONOMY)
    found = taxonomy.check(
        str(tmp_path),
        files=[path],
        events_registry={
            "run_start": {"kind": "point", "desc": "x"},
            "run_loop": {"kind": "span", "desc": "x"},
        },
        env_registry={},
        docs_path="docs/DOES_NOT_EXIST.md",
    )
    assert _codes(found) == ["ANL405"]
    assert found[0].severity == ERROR


def test_taxonomy_external_events_exempt_from_emission_check(tmp_path):
    path = _write(tmp_path, "pkg/emitters.py", GOOD_TAXONOMY)
    _write(tmp_path, "docs/OBS.md", _GOOD_DOCS + "| `child_only` | point | x |\n")
    found = taxonomy.check(
        str(tmp_path),
        files=[path],
        events_registry={
            "run_start": {"kind": "point", "desc": "x"},
            "run_loop": {"kind": "span", "desc": "x"},
            "child_only": {"kind": "point", "desc": "x", "external": True},
        },
        env_registry={},
        docs_path="docs/OBS.md",
    )
    assert found == []


# ---- knob-drift ----------------------------------------------------------

# a consistent five-surface snapshot to perturb per assertion
_KNOBS = ("backend", "halo")
_SPACE = ("backend", "halo", "mesh")
_FLAGS = ("--backend", "--halo")
_ROWS = {"backend", "halo", "platform"}
_ROUTES = ("platform",)
_DOC = "backend halo"


def _drift(**kw):
    args = dict(
        knobs=_KNOBS,
        space_keys=_SPACE,
        cli_flags=_FLAGS,
        row_strings=_ROWS,
        route_fields=_ROUTES,
        tuning_doc=_DOC,
    )
    args.update(kw)
    return knobs_checker.check(REPO, **args)


def test_knob_drift_quiet_on_agreeing_surfaces():
    assert _drift() == []


def test_knob_drift_fires_per_drifted_surface():
    # a knob SolverConfig does not carry
    assert "ANL501" in _codes(_drift(knobs=_KNOBS + ("bogus_knob",)))
    # the lattice searching a non-knob
    assert "ANL502" in _codes(_drift(space_keys=_SPACE + ("mystery",)))
    # a knob the lattice never searches
    assert "ANL503" in _codes(_drift(space_keys=("backend", "mesh")))
    # a knob with no CLI flag
    assert "ANL504" in _codes(_drift(cli_flags=("--backend",)))
    # a knob bench rows never record
    assert "ANL505" in _codes(_drift(row_strings={"backend", "platform"}))
    # a provenance-required field the harness never writes
    assert "ANL506" in _codes(_drift(route_fields=("platform", "new_route")))
    # an undocumented knob
    assert "ANL507" in _codes(_drift(tuning_doc="backend only"))


def test_harness_row_keys_ignore_docstrings(tmp_path):
    """'Recorded on bench rows' means a dict key (or string subscript
    assignment), not any mention — a knob named only in a docstring must
    still trip ANL505."""
    _write(
        tmp_path,
        "harness.py",
        '''
        """Mentions halo_order and platform in prose only."""

        def row(cfg):
            r = {"backend": cfg.backend}
            r["streamk_path"] = None
            return r
        ''',
    )
    keys = knobs_checker._harness_row_keys(str(tmp_path), "harness.py")
    assert keys == {"backend", "streamk_path"}


def test_knob_drift_live_surfaces_agree():
    """The real SolverConfig/lattice/CLI/harness/docs cross-check — the
    five surfaces agree today and this pins them together."""
    assert knobs_checker.check(REPO) == []


# ---- promoted data-lint cores -------------------------------------------


def _ledger_lines(events):
    return "\n".join(json.dumps(e) for e in events) + "\n"


def _evt(seq, name, kind="point", **extra):
    rec = dict(
        ts=1000.0 + seq,
        run_id="r1",
        proc=0,
        seq=seq,
        event=name,
        kind=kind,
    )
    rec.update(extra)
    return rec


def test_ledgerlint_taxonomy_flag_audits_stream_names(tmp_path):
    path = tmp_path / "ledger.jsonl"
    path.write_text(
        _ledger_lines(
            [
                _evt(0, "ledger_open"),
                _evt(1, "not_a_registered_event"),
                _evt(2, "ledger_close"),
            ]
        )
    )
    # schema-only: clean; with --taxonomy: the foreign name is a defect
    assert ledgerlint.check_file(str(path)) == []
    defects = ledgerlint.check_file(str(path), taxonomy=True)
    assert len(defects) == 1 and defects[0][0] == 2
    assert "not_a_registered_event" in defects[0][1]
    # and the finding-format view carries the shared schema
    findings = ledgerlint.check_file_findings(str(path), taxonomy=True)
    assert [f.code for f in findings] == ["DATA-LEDGER"]
    assert findings[0].severity == ERROR


def test_ledgerlint_schema_rules_survived_promotion(tmp_path):
    path = tmp_path / "ledger.jsonl"
    path.write_text(
        _ledger_lines(
            [
                _evt(0, "ledger_open"),
                _evt(0, "run_start"),  # seq not increasing
                {"event": "residual"},  # missing required fields
            ]
        )
    )
    descs = [d for _, d in ledgerlint.check_file(str(path))]
    assert any("seq" in d for d in descs)
    assert any("missing required field" in d for d in descs)


def test_obs_check_shim_still_exports_the_core():
    from heat3d_tpu.obs import check as obs_check

    assert obs_check.check_file is ledgerlint.check_file
    assert obs_check.main is ledgerlint.main


def test_provenance_findings_format(tmp_path):
    path = tmp_path / "rows.jsonl"
    path.write_text(json.dumps({"bench": "halo", "p50_ms": 1.0}) + "\n")
    findings = provenance.check_file_findings(str(path))
    assert findings and all(f.code == "DATA-PROV" for f in findings)
    descs = " ".join(f.message for f in findings)
    assert "ts" in descs and "sync_rtt_s" in descs


def test_provenance_script_wrapper_delegates(tmp_path):
    good = tmp_path / "rows.jsonl"
    good.write_text(
        json.dumps({"note": "foreign lines pass"}) + "\n"
        + json.dumps(
            {
                "bench": "halo", "ts": "t", "platform": "cpu",
                "sync_rtt_s": 0.1, "halo_plan": "monolithic",
            }
        )
        + "\n"
    )
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"bench": "halo"}) + "\n")
    run = lambda p: subprocess.run(  # noqa: E731
        [sys.executable, "scripts/check_provenance.py", "--start-line", "1", p],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    ok = run(str(good))
    assert ok.returncode == 0, ok.stderr
    fail = run(str(bad))
    assert fail.returncode == 1
    assert "sync_rtt_s" in fail.stderr


# ---- framework: suppression, baseline, rc --------------------------------


def _finding(line=7, message="collective 'lax.psum' is guarded"):
    return Finding(
        checker="collective-divergence",
        severity=ERROR,
        path="pkg/bad_coll.py",
        line=line,
        code="ANL101",
        symbol="process_guarded",
        message=message,
    )


def test_fingerprint_is_line_and_number_free():
    assert _finding(line=7).fingerprint() == _finding(line=99).fingerprint()
    anon = Finding(
        checker="c", severity=ERROR, path="p.py", line=1, code="X",
        message="budget 12 MiB over 16 MiB cap",
    )
    renum = Finding(
        checker="c", severity=ERROR, path="p.py", line=2, code="X",
        message="budget 13 MiB over 32 MiB cap",
    )
    assert anon.fingerprint() == renum.fingerprint()


def test_inline_ok_comment_suppresses_only_named_checker(tmp_path):
    path = _write(
        tmp_path,
        "pkg/bad_coll.py",
        """
        x = 1
        """,
    )
    lines = ["# pad\n"] * 10
    lines[6] = "    u = lax.psum(u, 'x')  # heat3d-lint: ok=collective-divergence\n"
    with open(path, "w") as f:
        f.writelines(lines)
    kept, suppressed = apply_suppressions(str(tmp_path), [_finding(line=7)], {})
    assert kept == [] and len(suppressed) == 1
    # a different checker's finding on the same line is NOT suppressed
    other = Finding(
        checker="vmem-budget", severity=ERROR, path="pkg/bad_coll.py",
        line=7, code="ANL301", message="m",
    )
    kept, suppressed = apply_suppressions(str(tmp_path), [other], {})
    assert kept == [other]


def test_baseline_round_trip_suppresses_grandfathered(tmp_path):
    baseline_path = str(tmp_path / ".heat3d-lint-baseline.json")
    f_old = _finding()
    assert write_baseline(baseline_path, [f_old]) == 1
    baseline = load_baseline(baseline_path)
    kept, suppressed = apply_suppressions(str(tmp_path), [f_old], baseline)
    assert kept == [] and suppressed == [f_old]
    # a NEW finding (different symbol) is not grandfathered
    f_new = Finding(
        checker=f_old.checker, severity=ERROR, path=f_old.path, line=3,
        code=f_old.code, symbol="fresh_function", message="m",
    )
    kept, _ = apply_suppressions(str(tmp_path), [f_new], baseline)
    assert kept == [f_new]


def test_write_baseline_keeps_still_firing_grandfathered(tmp_path, capsys):
    """Regenerating the baseline while a grandfathered finding still
    fires must keep it grandfathered — and entries owned by checkers not
    run this invocation survive untouched."""
    _write(tmp_path, "heat3d_tpu/bad.py", BAD_COLLECTIVES)
    baseline = str(tmp_path / ".heat3d-lint-baseline.json")
    args = ["--checker", "collective-divergence",
            "--root", str(tmp_path), "--baseline", baseline]
    assert lint_main(args + ["--write-baseline"]) == 0
    capsys.readouterr()
    # regenerate again: the old (still-firing) entries must not drop out
    assert lint_main(args + ["--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(args + ["--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"]["error"] == 0 and payload["suppressed"] > 0
    # a single-checker regeneration must not wipe other checkers' entries
    assert lint_main(
        ["--checker", "vmem-budget", "--root", str(tmp_path),
         "--baseline", baseline, "--write-baseline"]
    ) == 0
    capsys.readouterr()
    entries = load_baseline(baseline)
    assert any(
        e["checker"] == "collective-divergence" for e in entries.values()
    )


def test_broken_baseline_hides_nothing(tmp_path):
    p = tmp_path / "b.json"
    p.write_text("{not json")
    assert load_baseline(str(p)) == {}


def test_rc_policy_errors_only():
    warn = Finding(
        checker="c", severity=WARNING, path="p", line=0, code="X", message="m"
    )
    assert exit_code([warn]) == 0
    assert exit_code([warn, _finding()]) == 1
    assert exit_code([]) == 0


def test_crashed_checker_is_an_error_finding(monkeypatch):
    # astutil has no check(); a checker that cannot run must read as red
    monkeypatch.setitem(CHECKERS, "vmem-budget", "heat3d_tpu.analysis.astutil")
    found = run_checkers(REPO, ["vmem-budget"])
    assert _codes(found) == ["ANL000"]
    assert found[0].severity == ERROR
    # an unimportable checker is the same tripwire, not a traceback
    monkeypatch.setitem(CHECKERS, "vmem-budget", "heat3d_tpu.analysis.gone")
    assert _codes(run_checkers(REPO, ["vmem-budget"])) == ["ANL000"]


def test_write_baseline_never_grandfathers_checker_crashes(
    tmp_path, capsys, monkeypatch
):
    """A transiently broken checker at --write-baseline time must not be
    permanently suppressed (its ANL000 fingerprint is anchored on the
    checker name alone)."""
    (tmp_path / "heat3d_tpu").mkdir()
    baseline = str(tmp_path / ".heat3d-lint-baseline.json")
    monkeypatch.setitem(
        CHECKERS, "collective-divergence", "heat3d_tpu.analysis.astutil"
    )
    args = ["--checker", "collective-divergence",
            "--root", str(tmp_path), "--baseline", baseline]
    assert lint_main(args + ["--write-baseline"]) == 0
    capsys.readouterr()
    assert load_baseline(baseline) == {}
    assert lint_main(args) == 1  # the crash still reads red
    capsys.readouterr()


# ---- heat3d lint CLI -----------------------------------------------------


def test_lint_cli_unknown_checker_rejected():
    with pytest.raises(SystemExit):
        lint_main(["--checker", "no-such-checker"])


def test_lint_cli_single_checker_json(tmp_path, capsys):
    rc = lint_main(["--checker", "knob-drift", "--json", "--root", REPO])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["checkers"] == ["knob-drift"]
    assert payload["counts"]["error"] == 0


def test_lint_cli_write_baseline_then_clean(tmp_path, capsys, monkeypatch):
    """Grandfathering workflow: seeded errors -> rc 1; --write-baseline
    -> rc 0 afterwards, and the JSON reports them as suppressed."""
    _write(tmp_path, "heat3d_tpu/bad.py", BAD_COLLECTIVES)
    baseline = str(tmp_path / ".heat3d-lint-baseline.json")
    args = [
        "--checker", "collective-divergence",
        "--root", str(tmp_path), "--baseline", baseline,
    ]
    assert lint_main(args + ["--json"]) == 1
    capsys.readouterr()
    assert lint_main(args + ["--write-baseline"]) == 0
    capsys.readouterr()
    rc = lint_main(args + ["--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["counts"]["error"] == 0
    assert payload["suppressed"] > 0
    # --no-suppress is the audit view: everything comes back
    assert lint_main(args + ["--no-suppress"]) == 1
    capsys.readouterr()


def test_repo_is_lint_clean():
    """Acceptance: `heat3d lint --json` over this repo has zero
    unsuppressed error-severity findings — run exactly as CI runs it."""
    out = subprocess.run(
        [sys.executable, "-m", "heat3d_tpu.cli", "lint", "--json"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["counts"]["error"] == 0
    assert set(payload["checkers"]) == set(CHECKERS)
    errors = [f for f in payload["findings"] if f["severity"] == "error"]
    assert errors == []
