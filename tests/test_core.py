"""Unit tests: configs, stencil taps, decomposition index math, golden model
(SURVEY.md §4 'Unit' tier — the checks the reference never had)."""

import numpy as np
import pytest

from heat3d_tpu.core.config import (
    BoundaryCondition,
    GridConfig,
    MeshConfig,
    SolverConfig,
    StencilConfig,
    dims_create,
)
from heat3d_tpu.core import decomposition as dec
from heat3d_tpu.core import golden
from heat3d_tpu.core.stencils import STENCILS, nonzero_taps, stencil_taps


# ---- configs ---------------------------------------------------------------


def test_stable_dt_isotropic():
    g = GridConfig.cube(8, alpha=2.0)
    assert g.stable_dt() == pytest.approx(1.0 / (2.0 * 2.0 * 3.0))


def test_solver_config_uneven_padding():
    cfg = SolverConfig(grid=GridConfig.cube(10), mesh=MeshConfig(shape=(4, 1, 1)))
    assert cfg.is_padded
    assert cfg.padded_shape == (12, 10, 10)
    assert cfg.local_shape == (3, 10, 10)
    even = SolverConfig(grid=GridConfig.cube(8), mesh=MeshConfig(shape=(4, 1, 1)))
    assert not even.is_padded and even.padded_shape == (8, 8, 8)


def test_solver_config_rejects_uneven_periodic():
    with pytest.raises(ValueError, match="periodic"):
        SolverConfig(
            grid=GridConfig.cube(10),
            mesh=MeshConfig(shape=(4, 1, 1)),
            stencil=StencilConfig(bc=BoundaryCondition.PERIODIC),
        )


def test_dims_create_balanced():
    assert dims_create(8) == (2, 2, 2)
    assert dims_create(64) == (4, 4, 4)
    assert dims_create(1) == (1, 1, 1)
    assert dims_create(12) in ((3, 2, 2),)
    px, py, pz = dims_create(7)
    assert px * py * pz == 7


def test_unknown_stencil_rejected():
    with pytest.raises(ValueError, match="unknown stencil"):
        StencilConfig(kind="9pt")


# ---- stencil taps ----------------------------------------------------------


def test_taps_sum_to_one():
    # Laplacian weights sum to zero => update taps sum to one (a constant
    # field is a steady state under periodic BC).
    for name, st in STENCILS.items():
        taps = stencil_taps(st, alpha=0.7, dt=0.05, spacing=(1.0, 1.0, 1.0))
        assert taps.sum() == pytest.approx(1.0, abs=1e-12), name


def test_7pt_tap_values():
    taps = stencil_taps(STENCILS["7pt"], alpha=1.0, dt=0.1, spacing=(1.0, 1.0, 1.0))
    assert taps[1, 1, 1] == pytest.approx(1.0 - 0.6)
    assert taps[0, 1, 1] == pytest.approx(0.1)
    assert np.count_nonzero(taps) == 7


def test_7pt_anisotropic_spacing():
    taps = stencil_taps(STENCILS["7pt"], alpha=1.0, dt=0.01, spacing=(1.0, 2.0, 4.0))
    assert taps[0, 1, 1] == pytest.approx(0.01 / 1.0)
    assert taps[1, 0, 1] == pytest.approx(0.01 / 4.0)
    assert taps[1, 1, 0] == pytest.approx(0.01 / 16.0)
    assert taps.sum() == pytest.approx(1.0)


def test_27pt_requires_uniform_spacing():
    with pytest.raises(ValueError, match="uniform spacing"):
        stencil_taps(STENCILS["27pt"], alpha=1.0, dt=0.01, spacing=(1.0, 1.0, 2.0))


def test_27pt_has_27_taps():
    taps = stencil_taps(STENCILS["27pt"], 1.0, 0.01, (1.0, 1.0, 1.0))
    assert np.count_nonzero(taps) == 27
    assert len(list(nonzero_taps(taps))) == 27


# ---- golden model ----------------------------------------------------------


def test_golden_hand_computed_center():
    # 3x3x3 field, single hot center cell, one 7pt step, Dirichlet-0:
    # center:  c0*1 = 1-6r ; face neighbors: r each.
    u = np.zeros((3, 3, 3), dtype=np.float32)
    u[1, 1, 1] = 1.0
    g = GridConfig.cube(3, dt=0.1)
    taps = stencil_taps(STENCILS["7pt"], 1.0, 0.1, (1.0, 1.0, 1.0))
    out = golden.step(u, taps)
    assert out[1, 1, 1] == pytest.approx(1.0 - 0.6)
    assert out[0, 1, 1] == pytest.approx(0.1)
    assert out[1, 0, 1] == pytest.approx(0.1)
    assert out[1, 1, 2] == pytest.approx(0.1)
    assert out[0, 0, 1] == 0.0  # edge cell: no mass after one step


def test_golden_conservation_periodic():
    u = golden.random_init((6, 7, 8), seed=3).astype(np.float64)
    taps = stencil_taps(STENCILS["27pt"], 1.0, 0.02, (1.0, 1.0, 1.0))
    out = golden.step(u, taps, bc=BoundaryCondition.PERIODIC)
    assert out.sum() == pytest.approx(u.sum(), rel=1e-12)


def test_golden_constant_steady_state():
    u = np.full((5, 5, 5), 3.25)
    for name in STENCILS:
        taps = stencil_taps(STENCILS[name], 1.0, 0.05, (1.0, 1.0, 1.0))
        out = golden.step(u, taps, bc=BoundaryCondition.PERIODIC)
        np.testing.assert_allclose(out, u, rtol=1e-13)
        # Dirichlet with matching bc_value is also steady
        out = golden.step(
            u, taps, bc=BoundaryCondition.DIRICHLET, bc_value=3.25
        )
        np.testing.assert_allclose(out, u, rtol=1e-13)


def test_golden_decay_dirichlet():
    # With zero Dirichlet BC heat leaks out: norm strictly decreases.
    u = golden.gaussian_init((10, 10, 10)).astype(np.float64)
    g = GridConfig.cube(10)
    taps = stencil_taps(STENCILS["7pt"], 1.0, g.effective_dt(), (1.0, 1.0, 1.0))
    norms = [np.abs(u).sum()]
    for _ in range(5):
        u = golden.step(u, taps)
        norms.append(np.abs(u).sum())
    assert all(b < a for a, b in zip(norms, norms[1:]))


def test_init_block_matches_full():
    shape = (12, 10, 8)
    for name in ("hot-cube", "gaussian", "random"):
        full = golden.make_init(name, shape, seed=5)
        block = golden.make_init_block(
            name, shape, (slice(3, 9), slice(0, 5), slice(4, 8)), seed=5
        )
        np.testing.assert_array_equal(full[3:9, 0:5, 4:8], block)


# ---- decomposition ---------------------------------------------------------


def test_coords_roundtrip():
    mesh = (2, 3, 4)
    for r in range(24):
        assert dec.rank_of_coords(dec.coords_of_rank(r, mesh), mesh) == r


def test_local_extent_uneven():
    # 10 cells over 3 parts -> 4,3,3 with correct offsets
    assert dec.local_extent(10, 3, 0) == (0, 4)
    assert dec.local_extent(10, 3, 1) == (4, 3)
    assert dec.local_extent(10, 3, 2) == (7, 3)
    # cover the whole range exactly once
    total = sum(dec.local_extent(10, 3, i)[1] for i in range(3))
    assert total == 10


def test_subdomains_tile_grid():
    grid, mesh = (8, 9, 10), (2, 3, 1)
    seen = np.zeros(grid, dtype=int)
    for sd in dec.all_subdomains(grid, mesh):
        seen[sd.slices] += 1
    assert (seen == 1).all()


def test_neighbor_rank_edges():
    mesh = (3, 1, 1)
    assert dec.neighbor_rank(0, mesh, 0, -1, periodic=False) is None
    assert dec.neighbor_rank(0, mesh, 0, -1, periodic=True) == 2
    assert dec.neighbor_rank(2, mesh, 0, +1, periodic=False) is None
    assert dec.neighbor_rank(1, mesh, 0, +1, periodic=False) == 2


def test_split_x_symmetric_contract(monkeypatch):
    from heat3d_tpu.core.stencils import flat_taps, split_x_symmetric

    monkeypatch.delenv("HEAT3D_FACTOR_7PT", raising=False)
    taps27 = stencil_taps(STENCILS["27pt"], 0.1, 0.05, (1.0, 1.0, 1.0))
    sym = split_x_symmetric(flat_taps(taps27))
    assert sym is not None
    a_taps, b_taps = sym
    assert len(a_taps) == 9 and len(b_taps) == 9
    # A is exactly the shared +-x plane pattern, in nonzero_taps order
    assert a_taps == [
        (dj, dk, w) for (di, dj, dk), w in nonzero_taps(taps27) if di == -1
    ]

    # the 7-point set keeps the measured headline chain by default...
    taps7 = stencil_taps(STENCILS["7pt"], 0.1, 0.05, (1.0, 1.0, 1.0))
    assert split_x_symmetric(flat_taps(taps7)) is None
    # ...and factors under the A/B knob (off-values stay off)
    monkeypatch.setenv("HEAT3D_FACTOR_7PT", "1")
    assert split_x_symmetric(flat_taps(taps7)) is not None
    monkeypatch.setenv("HEAT3D_FACTOR_7PT", "0")
    assert split_x_symmetric(flat_taps(taps7)) is None
    monkeypatch.delenv("HEAT3D_FACTOR_7PT")

    # an x-asymmetric set must never factor
    flat = flat_taps(taps27)
    broken = tuple(
        (di, dj, dk, w * 2 if di == 1 else w) for di, dj, dk, w in flat
    )
    assert split_x_symmetric(broken) is None


def _ref_term(u):
    """Reference implementation of the full term contract (xsum + ysum)."""
    nx, ny, nz = u.shape[0] - 2, u.shape[1] - 2, u.shape[2] - 2

    def term(di, dj, dk):
        if di == "xsum":
            src = u[0:nx] + u[2 : 2 + nx]
        else:
            src = u[1 + di : 1 + di + nx]
        if dj == "ysum":
            row = src[:, 0:ny] + src[:, 2 : 2 + ny]
            return row[:, :, 1 + dk : 1 + dk + nz]
        return src[:, 1 + dj : 1 + dj + ny, 1 + dk : 1 + dk + nz]

    return term


def test_accumulate_taps_factored_matches_plain(monkeypatch):
    from heat3d_tpu.core.stencils import accumulate_taps, flat_taps

    rng = np.random.default_rng(7)
    u = rng.standard_normal((5, 6, 7))
    taps = stencil_taps(STENCILS["27pt"], 0.13, 0.04, (1.0, 1.0, 1.0))
    flat = flat_taps(taps)
    nx, ny, nz = u.shape[0] - 2, u.shape[1] - 2, u.shape[2] - 2

    want = sum(
        w * u[1 + di : 1 + di + nx, 1 + dj : 1 + dj + ny, 1 + dk : 1 + dk + nz]
        for di, dj, dk, w in flat
    )
    # both factoring levels and the unfactored-y variant agree with plain
    for fy in ("1", "0"):
        monkeypatch.setenv("HEAT3D_FACTOR_Y", fy)
        got = accumulate_taps(flat, _ref_term(u), float)
        np.testing.assert_allclose(got, want, rtol=1e-13, atol=1e-14)


def test_split_y_symmetric_contract():
    from heat3d_tpu.core.stencils import (
        flat_taps,
        split_x_symmetric,
        split_y_symmetric,
    )

    taps27 = stencil_taps(STENCILS["27pt"], 0.1, 0.05, (1.0, 1.0, 1.0))
    a_taps, b_taps = split_x_symmetric(flat_taps(taps27))
    for plane in (a_taps, b_taps):  # both 27pt planes are y-symmetric 3x3
        r, m = split_y_symmetric(plane)
        assert len(r) == 3 and len(m) == 3
        assert r == [(dk, w) for dj, dk, w in plane if dj == -1]
    # a y-asymmetric plane must never factor
    broken = [(dj, dk, w * 2 if dj == 1 else w) for dj, dk, w in a_taps]
    assert split_y_symmetric(broken) is None


def test_effective_num_taps_matches_factoring(monkeypatch):
    """The VMEM-stack estimate tracks the factored chain: 15 live
    temporaries for the x+y-factored 27pt (12 terms + xsum plane + 2 row
    caches), 19 with y-factoring off, 7 for the unfactored 7pt."""
    from heat3d_tpu.core.stencils import effective_num_taps

    monkeypatch.delenv("HEAT3D_FACTOR_7PT", raising=False)
    monkeypatch.setenv("HEAT3D_FACTOR_Y", "1")
    assert effective_num_taps(STENCILS["27pt"].weights) == 15
    assert effective_num_taps(STENCILS["7pt"].weights) == 7
    monkeypatch.setenv("HEAT3D_FACTOR_Y", "0")
    assert effective_num_taps(STENCILS["27pt"].weights) == 19


def test_accumulate_taps_y_factoring_op_counts(monkeypatch):
    """The factored 27pt chain emits 12 terms (3+3 per plane) with y-
    factoring on, 18 with it off — the measurable op-count contract."""
    from heat3d_tpu.core.stencils import accumulate_taps, flat_taps

    taps = stencil_taps(STENCILS["27pt"], 0.13, 0.04, (1.0, 1.0, 1.0))
    flat = flat_taps(taps)
    u = np.random.default_rng(3).standard_normal((5, 6, 7))

    for fy, n_terms, n_ysum in (("1", 12, 6), ("0", 18, 0)):
        calls = []
        ref = _ref_term(u)

        def term(di, dj, dk, ref=ref):
            calls.append((di, dj, dk))
            return ref(di, dj, dk)

        monkeypatch.setenv("HEAT3D_FACTOR_Y", fy)
        accumulate_taps(flat, term, float)
        assert len(calls) == n_terms, (fy, calls)
        assert sum(c[1] == "ysum" for c in calls) == n_ysum


def test_27pt_symbol_isotropy():
    """The judged 27-point stencil's raison d'etre (BASELINE.json config
    4: 'higher-order'): its Fourier symbol is direction-ISOTROPIC to
    leading error order, unlike the 7-point's. For wave vectors of equal
    magnitude along the axis, face-diagonal, and body-diagonal
    directions, the 27pt Laplacian symbol's directional spread must be
    far smaller than the 7pt's, and both must be consistent
    (symbol -> -|k|^2 as k -> 0)."""

    def symbol(weights, k):
        # lambda(k) = sum_d w_d * exp(i k . d); real by symmetry
        s = 0.0
        for (di, dj, dk), w in np.ndenumerate(weights):
            s += w * np.cos(np.dot(k, (di - 1, dj - 1, dk - 1)))
        return s

    def spread(weights, kmag):
        dirs = [
            np.array([1.0, 0.0, 0.0]),
            np.array([1.0, 1.0, 0.0]) / np.sqrt(2),
            np.array([1.0, 1.0, 1.0]) / np.sqrt(3),
        ]
        vals = [symbol(weights, kmag * d) for d in dirs]
        return (max(vals) - min(vals)) / abs(min(vals))

    w7 = STENCILS["7pt"].weights
    w27 = STENCILS["27pt"].weights
    kmag = 0.5  # |k|h = 0.5: resolved but finite-h regime
    s7, s27 = spread(w7, kmag), spread(w27, kmag)
    # isotropic leading error: directional spread collapses by >= 20x
    assert s27 < s7 / 20, (s7, s27)
    # consistency: both symbols approach -|k|^2 in the continuum limit
    for w in (w7, w27):
        k = 1e-3
        assert abs(symbol(w, np.array([k, 0, 0])) / (-(k**2)) - 1) < 1e-5
        kd = np.array([1.0, 1.0, 1.0]) / np.sqrt(3) * k
        assert abs(symbol(w, kd) / (-(k**2)) - 1) < 1e-5
