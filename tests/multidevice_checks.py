"""Real multi-device distributed checks, run on an 8-device CPU mesh.

Executed as a subprocess by tests/test_multidevice.py with the axon PJRT
plugin disabled (env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8) — the software
equivalent of the reference class's ``mpirun -np 8`` oversubscription test
(SURVEY.md §4): the decomposed run must reproduce the undecomposed run.

Not named test_* so pytest does not collect it in the main (axon) process.
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from heat3d_tpu.core import golden
from heat3d_tpu.core.config import (
    BoundaryCondition,
    GridConfig,
    MeshConfig,
    Precision,
    SolverConfig,
    StencilConfig,
)
from heat3d_tpu.core.stencils import STENCILS, stencil_taps
from heat3d_tpu.ops.stencil_jnp import step_single_device
from heat3d_tpu.parallel.halo import exchange_halo
from heat3d_tpu.parallel.step import make_multistep_fn, make_step_fn
from heat3d_tpu.parallel.topology import build_mesh, field_sharding
from heat3d_tpu.utils.compat import shard_map


def check_step_matches_single_device():
    """Decomposed step == undecomposed step, across mesh shapes, stencils,
    BCs, and precisions — the '-np 1 vs -np P' oracle."""
    grid = (16, 16, 16)
    u_host = golden.random_init(grid, seed=7)
    for mesh_shape in [(8, 1, 1), (2, 2, 2), (1, 2, 4), (2, 4, 1)]:
        for kind in ("7pt", "27pt"):
            for bc, bcv in [
                (BoundaryCondition.DIRICHLET, 0.0),
                (BoundaryCondition.DIRICHLET, 1.5),
                (BoundaryCondition.PERIODIC, 0.0),
            ]:
                cfg = SolverConfig(
                    grid=GridConfig(shape=grid),
                    stencil=StencilConfig(kind=kind, bc=bc, bc_value=bcv),
                    mesh=MeshConfig(shape=mesh_shape),
                    backend="jnp",
                )
                mesh = build_mesh(cfg.mesh)
                sharding = field_sharding(mesh, cfg.mesh)
                u = jax.device_put(jnp.asarray(u_host), sharding)
                got = jax.jit(make_step_fn(cfg, mesh))(u)
                taps = stencil_taps(
                    STENCILS[kind], cfg.grid.alpha, cfg.grid.effective_dt(),
                    cfg.grid.spacing,
                )
                want = step_single_device(jnp.asarray(u_host), taps, bc, bcv)
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6,
                    err_msg=f"mesh={mesh_shape} kind={kind} bc={bc} bcv={bcv}",
                )
    print("step_matches_single_device OK")


def check_faces_direct_step_distributed():
    """Multi-chip direct step (faces-only exchange + BC-fused bulk kernel +
    shell patches, interpret-mode kernel) == the single-device oracle across
    mesh shapes, stencils, and BCs. Exercises exchange_halo_faces' corner
    propagation and _padded_slab reassembly on real device boundaries."""
    import os

    from heat3d_tpu.parallel.step import _direct_kernel_fn

    prior = os.environ.get("HEAT3D_DIRECT_INTERPRET")
    os.environ["HEAT3D_DIRECT_INTERPRET"] = "1"
    try:
        grid = (16, 16, 16)
        u_host = golden.random_init(grid, seed=23)
        for mesh_shape in [(8, 1, 1), (2, 2, 2), (1, 2, 4), (2, 4, 1)]:
            for kind in ("7pt", "27pt"):
                for bc, bcv in [
                    (BoundaryCondition.DIRICHLET, 1.5),
                    (BoundaryCondition.PERIODIC, 0.0),
                ]:
                    cfg = SolverConfig(
                        grid=GridConfig(shape=grid),
                        stencil=StencilConfig(kind=kind, bc=bc, bc_value=bcv),
                        mesh=MeshConfig(shape=mesh_shape),
                        backend="auto",
                    )
                    assert _direct_kernel_fn(cfg, 1, multichip=True) is not None
                    mesh = build_mesh(cfg.mesh)
                    u = jax.device_put(
                        jnp.asarray(u_host), field_sharding(mesh, cfg.mesh)
                    )
                    got = jax.jit(make_step_fn(cfg, mesh))(u)
                    taps = stencil_taps(
                        STENCILS[kind], cfg.grid.alpha,
                        cfg.grid.effective_dt(), cfg.grid.spacing,
                    )
                    want = step_single_device(jnp.asarray(u_host), taps, bc, bcv)
                    np.testing.assert_allclose(
                        np.asarray(got), np.asarray(want),
                        rtol=1e-6, atol=1e-6,
                        err_msg=f"mesh={mesh_shape} kind={kind} bc={bc}",
                    )
        # bf16 storage: faces-direct == exchange path to bf16 rounding
        cfg = SolverConfig(
            grid=GridConfig(shape=grid),
            stencil=StencilConfig(kind="7pt"),
            mesh=MeshConfig(shape=(2, 2, 2)),
            precision=Precision.bf16(),
            backend="auto",
        )
        assert _direct_kernel_fn(cfg, 1, multichip=True) is not None
        mesh = build_mesh(cfg.mesh)
        u = jax.device_put(
            jnp.asarray(u_host, jnp.bfloat16), field_sharding(mesh, cfg.mesh)
        )
        got = jax.jit(make_step_fn(cfg, mesh))(u)
        import dataclasses as _dc

        want_bf16 = jax.jit(make_step_fn(_dc.replace(cfg, backend="jnp"), mesh))(u)
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32),
            np.asarray(want_bf16, dtype=np.float32),
            rtol=2e-2, atol=1e-2,
        )
    finally:
        if prior is None:
            os.environ.pop("HEAT3D_DIRECT_INTERPRET", None)
        else:
            os.environ["HEAT3D_DIRECT_INTERPRET"] = prior
    print("faces_direct_step_distributed OK (incl. bf16)")


def check_faces_direct_superstep_distributed():
    """Multi-chip tb=2 faces-direct superstep (width-2 faces exchange +
    fused direct2 bulk kernel + 2-deep shell patches, interpret-mode
    kernel) == two plain exchange-path steps, across mesh shapes, stencils,
    and BCs."""
    import dataclasses
    import os

    from heat3d_tpu.parallel.step import _direct_kernel_fn, make_superstep_fn

    prior = os.environ.get("HEAT3D_DIRECT_INTERPRET")
    os.environ["HEAT3D_DIRECT_INTERPRET"] = "1"
    try:
        grid = (16, 16, 16)
        u_host = golden.random_init(grid, seed=31)
        for mesh_shape in [(8, 1, 1), (2, 2, 2), (1, 2, 4), (2, 4, 1)]:
            for kind in ("7pt", "27pt"):
                for bc, bcv in [
                    (BoundaryCondition.DIRICHLET, 1.5),
                    (BoundaryCondition.PERIODIC, 0.0),
                ]:
                    cfg = SolverConfig(
                        grid=GridConfig(shape=grid),
                        stencil=StencilConfig(kind=kind, bc=bc, bc_value=bcv),
                        mesh=MeshConfig(shape=mesh_shape),
                        backend="auto",
                        time_blocking=2,
                    )
                    assert _direct_kernel_fn(cfg, 2, multichip=True) is not None
                    mesh = build_mesh(cfg.mesh)
                    u = jax.device_put(
                        jnp.asarray(u_host), field_sharding(mesh, cfg.mesh)
                    )
                    got = jax.jit(make_superstep_fn(cfg, mesh))(u)
                    cfg1 = dataclasses.replace(
                        cfg, time_blocking=1, backend="jnp"
                    )
                    s1 = jax.jit(make_step_fn(cfg1, mesh))
                    want = s1(s1(u))
                    np.testing.assert_allclose(
                        np.asarray(got), np.asarray(want),
                        rtol=1e-6, atol=1e-6,
                        err_msg=f"mesh={mesh_shape} kind={kind} bc={bc}",
                    )
    finally:
        if prior is None:
            os.environ.pop("HEAT3D_DIRECT_INTERPRET", None)
        else:
            os.environ["HEAT3D_DIRECT_INTERPRET"] = prior
    print("faces_direct_superstep_distributed OK")


def check_overlap_step_distributed():
    """Overlap (interior/boundary split) step == unsplit step on real
    multi-device meshes — the correctness half of SURVEY.md §7.3 item 2."""
    import dataclasses

    # 24 along x so the (8,1,1) slab still leaves a >=3-cell local interior
    grid = (24, 16, 16)
    u_host = golden.random_init(grid, seed=13)
    for mesh_shape in [(8, 1, 1), (2, 2, 2), (1, 2, 4)]:
        for kind in ("7pt", "27pt"):
            for bc in (BoundaryCondition.DIRICHLET, BoundaryCondition.PERIODIC):
                cfg = SolverConfig(
                    grid=GridConfig(shape=grid),
                    stencil=StencilConfig(kind=kind, bc=bc),
                    mesh=MeshConfig(shape=mesh_shape),
                    backend="jnp",
                )
                mesh = build_mesh(cfg.mesh)
                u = jax.device_put(
                    jnp.asarray(u_host), field_sharding(mesh, cfg.mesh)
                )
                got = jax.jit(
                    make_step_fn(dataclasses.replace(cfg, overlap=True), mesh)
                )(u)
                want = jax.jit(make_step_fn(cfg, mesh))(u)
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6,
                    err_msg=f"mesh={mesh_shape} kind={kind} bc={bc}",
                )
    print("overlap_step_distributed OK")


def check_uneven_decomposition():
    """Grids not divisible by the mesh run via bc-value storage padding and
    still match the golden model on the true extents (SURVEY.md §7.3 item 4,
    which the reference class sidesteps by requiring divisibility)."""
    from heat3d_tpu.models.heat3d import HeatSolver3D

    for grid, mesh_shape in [
        ((10, 16, 16), (8, 1, 1)),   # padding thicker than some local blocks
        ((9, 10, 11), (2, 2, 2)),
        ((24, 9, 10), (1, 2, 4)),
    ]:
        for kind in ("7pt", "27pt"):
            for bc_value in (0.0, 0.5):
                cfg = SolverConfig(
                    grid=GridConfig(shape=grid),
                    stencil=StencilConfig(
                        kind=kind, bc=BoundaryCondition.DIRICHLET,
                        bc_value=bc_value,
                    ),
                    mesh=MeshConfig(shape=mesh_shape),
                    backend="jnp",
                )
                solver = HeatSolver3D(cfg)
                u = solver.init_state("gaussian")
                u = solver.run(u, 3)
                want = golden.run(
                    golden.gaussian_init(grid).astype(np.float64),
                    cfg.grid, cfg.stencil, 3,
                )
                np.testing.assert_allclose(
                    solver.gather(u), want, rtol=1e-5, atol=1e-6,
                    err_msg=f"grid={grid} mesh={mesh_shape} kind={kind} "
                    f"bc_value={bc_value}",
                )
    print("uneven_decomposition OK")


def check_device_init_distributed():
    """The on-device hot-cube/zeros builders (models.heat3d._device_field)
    == the host block path, bitwise, on real multi-device meshes including
    uneven decompositions (storage padding pinned at bc_value lives on
    shards the device path must also pin)."""
    import os

    from heat3d_tpu.models.heat3d import HeatSolver3D

    for grid, mesh_shape in [
        ((16, 16, 16), (2, 2, 2)),
        ((9, 10, 11), (2, 2, 2)),    # uneven: padding on every axis
        ((10, 16, 16), (8, 1, 1)),   # padding thicker than some blocks
    ]:
        for prec, bc_value in [
            (Precision.fp32(), 0.0),
            (Precision.bf16(), 1.5),
        ]:
            cfg = SolverConfig(
                grid=GridConfig(shape=grid),
                stencil=StencilConfig(
                    kind="7pt", bc=BoundaryCondition.DIRICHLET,
                    bc_value=bc_value,
                ),
                mesh=MeshConfig(shape=mesh_shape),
                precision=prec,
                backend="jnp",
            )
            solver = HeatSolver3D(cfg)
            prior = os.environ.get("HEAT3D_DEVICE_INIT")
            os.environ["HEAT3D_DEVICE_INIT"] = "0"
            try:
                host_hot = np.asarray(solver.init_state("hot-cube"))
                host_zero = np.asarray(solver.zeros_state())
                os.environ["HEAT3D_DEVICE_INIT"] = "1"
                dev_hot = np.asarray(solver.init_state("hot-cube"))
                dev_zero = np.asarray(solver.zeros_state())
            finally:
                if prior is None:
                    os.environ.pop("HEAT3D_DEVICE_INIT", None)
                else:
                    os.environ["HEAT3D_DEVICE_INIT"] = prior
            np.testing.assert_array_equal(
                dev_hot, host_hot,
                err_msg=f"hot-cube grid={grid} mesh={mesh_shape}",
            )
            np.testing.assert_array_equal(
                dev_zero, host_zero,
                err_msg=f"zeros grid={grid} mesh={mesh_shape}",
            )
    print("device_init_distributed OK")


def check_time_blocking_distributed():
    """Temporally-blocked supersteps == plain steps on real multi-device
    meshes, including uneven decompositions (where the intermediate's
    padding/ghost pinning is the subtle part)."""
    import dataclasses

    for grid, mesh_shape, kind, bc, k in [
        ((16, 16, 16), (2, 2, 2), "7pt", BoundaryCondition.DIRICHLET, 2),
        ((16, 16, 16), (2, 2, 2), "27pt", BoundaryCondition.PERIODIC, 2),
        ((16, 16, 16), (8, 1, 1), "27pt", BoundaryCondition.DIRICHLET, 2),
        ((10, 9, 16), (2, 2, 2), "7pt", BoundaryCondition.DIRICHLET, 2),  # uneven
        # k=3: real cross-device width-3 ppermutes + 2-then-1-ring mid fills
        ((16, 16, 16), (2, 2, 2), "7pt", BoundaryCondition.DIRICHLET, 3),
        ((16, 16, 16), (2, 2, 2), "27pt", BoundaryCondition.PERIODIC, 3),
        ((16, 16, 16), (2, 2, 2), "7pt", BoundaryCondition.DIRICHLET, 4),
    ]:
        cfg = SolverConfig(
            grid=GridConfig(shape=grid),
            stencil=StencilConfig(kind=kind, bc=bc, bc_value=0.5
                                  if bc is BoundaryCondition.DIRICHLET else 0.0),
            mesh=MeshConfig(shape=mesh_shape),
            backend="jnp",
        )
        cfg2 = dataclasses.replace(cfg, time_blocking=k)
        u_host = golden.random_init(grid, seed=17)
        from heat3d_tpu.models.heat3d import HeatSolver3D

        s1 = HeatSolver3D(cfg)
        s2 = HeatSolver3D(cfg2)
        u1 = s1.run(s1.init_state(u_host), 5)
        u2 = s2.run(s2.init_state(u_host), 5)
        np.testing.assert_allclose(
            s1.gather(u1), s2.gather(u2), rtol=1e-6, atol=1e-6,
            err_msg=f"grid={grid} mesh={mesh_shape} kind={kind} bc={bc}",
        )
    print("time_blocking_distributed OK")


def check_bf16_distributed():
    grid = (16, 16, 16)
    cfg = SolverConfig(
        grid=GridConfig(shape=grid),
        stencil=StencilConfig(kind="7pt"),
        mesh=MeshConfig(shape=(2, 2, 2)),
        precision=Precision.bf16(),
        backend="jnp",
    )
    mesh = build_mesh(cfg.mesh)
    u_host = golden.gaussian_init(grid)
    u = jax.device_put(
        jnp.asarray(u_host, jnp.bfloat16), field_sharding(mesh, cfg.mesh)
    )
    got, r2 = jax.jit(make_step_fn(cfg, mesh, with_residual=True))(u)
    assert got.dtype == jnp.bfloat16
    assert r2.dtype == jnp.float32
    # single-device same policy
    cfg1 = SolverConfig(
        grid=GridConfig(shape=grid), stencil=cfg.stencil,
        mesh=MeshConfig(shape=(1, 1, 1)), precision=cfg.precision, backend="jnp",
    )
    mesh1 = build_mesh(cfg1.mesh, devices=jax.devices()[:1])
    want, r2_1 = jax.jit(make_step_fn(cfg1, mesh1, with_residual=True))(
        jax.device_put(jnp.asarray(u_host, jnp.bfloat16),
                       field_sharding(mesh1, cfg1.mesh))
    )
    np.testing.assert_array_equal(
        np.asarray(got.astype(jnp.float32)), np.asarray(want.astype(jnp.float32))
    )
    # the 8-way psum reduces partial sums in a different order than the
    # single-device sum — identical values, different fp32 rounding path
    assert float(r2) == pytest.approx(float(r2_1), rel=1e-5)
    print("bf16_distributed OK")


def check_halo_ghost_identity():
    """Rank-constant shards: after exchange, each ghost layer holds the
    neighbor's rank id (periodic wrap included) — the direct analogue of the
    reference's ghost-fill correctness check (SURVEY.md §4)."""
    mesh_cfg = MeshConfig(shape=(2, 2, 2))
    mesh = build_mesh(mesh_cfg)
    local = (4, 4, 4)
    grid = tuple(l * p for l, p in zip(local, mesh_cfg.shape))

    def rank_field():
        # global array whose value in each shard is its linear device index
        def linear_rank(x, y, z):
            return (x // local[0]) * 4 + (y // local[1]) * 2 + (z // local[2])

        idx = np.indices(grid)
        return jnp.asarray(linear_rank(*idx).astype(np.float32))

    u = jax.device_put(rank_field(), field_sharding(mesh, mesh_cfg))

    for bc in (BoundaryCondition.PERIODIC, BoundaryCondition.DIRICHLET):
        f = jax.jit(
            shard_map(
                lambda x: exchange_halo(x, mesh_cfg, bc, bc_value=-1.0),
                mesh=mesh,
                in_specs=P("x", "y", "z"),
                out_specs=P("x", "y", "z"),
            )
        )
        padded = f(u)  # global (2*(4+2),)*3 array of per-shard padded blocks
        blocks = np.asarray(padded).reshape(2, 6, 2, 6, 2, 6).transpose(
            0, 2, 4, 1, 3, 5
        )  # [px,py,pz][local 6,6,6]
        for px in range(2):
            for py in range(2):
                for pz in range(2):
                    b = blocks[px, py, pz]
                    me = px * 4 + py * 2 + pz
                    assert (b[1:-1, 1:-1, 1:-1] == me).all()
                    # x-low ghost: neighbor (px-1, py, pz); with size-2 axes,
                    # periodic wrap neighbor == the other device
                    for axis, (lo_nb, hi_nb) in enumerate(
                        [
                            ((1 - px) * 4 + py * 2 + pz,) * 2,
                            (px * 4 + (1 - py) * 2 + pz,) * 2,
                            (px * 4 + py * 2 + (1 - pz),) * 2,
                        ]
                    ):
                        coord = (px, py, pz)[axis]
                        sl_lo = [slice(1, -1)] * 3
                        sl_hi = [slice(1, -1)] * 3
                        sl_lo[axis] = 0
                        sl_hi[axis] = 5
                        lo = b[tuple(sl_lo)]
                        hi = b[tuple(sl_hi)]
                        if bc is BoundaryCondition.PERIODIC:
                            assert (lo == lo_nb).all(), (axis, coord, "lo")
                            assert (hi == hi_nb).all(), (axis, coord, "hi")
                        else:
                            # domain-boundary ghosts hold bc_value, interior
                            # ghosts hold the neighbor id
                            assert (lo == (-1.0 if coord == 0 else lo_nb)).all()
                            assert (hi == (-1.0 if coord == 1 else hi_nb)).all()
    print("halo_ghost_identity OK")


def check_multistep_vs_golden():
    grid = (16, 16, 16)
    cfg = SolverConfig(
        grid=GridConfig(shape=grid),
        stencil=StencilConfig(kind="27pt", bc=BoundaryCondition.PERIODIC),
        mesh=MeshConfig(shape=(2, 2, 2)),
        backend="jnp",
    )
    mesh = build_mesh(cfg.mesh)
    u_host = golden.gaussian_init(grid)
    u = jax.device_put(jnp.asarray(u_host), field_sharding(mesh, cfg.mesh))
    got = jax.jit(make_multistep_fn(cfg, mesh))(u, jnp.int32(5))
    want = golden.run(u_host.astype(np.float64), cfg.grid, cfg.stencil, 5)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)
    print("multistep_vs_golden OK")


def check_dma_halo_ring_interpret():
    """Pallas RDMA halo exchange (interpret mode) on a real 8-device ring ==
    the ppermute exchange, for every array axis (width-1 zero-staging fast
    path and axis-leading slab staging alike) and ghost widths 1..3, periodic and Dirichlet.

    jax 0.9's interpret mode cannot discharge remote DMA on meshes with >1
    named axis (dma_start_p NotImplementedError, MESH and LOGICAL device-id
    forms alike — verified; the check binds to the shard_map MESH, so even
    an (8,1,1) 3-named-axis mesh is rejected, which is why no full-step
    DMA execution check exists off-TPU), so multi-axis composition executes
    only on real multi-chip hardware; here each array axis is driven on a
    1D mesh and the 3D composition is covered by the TPU lowering tests
    (tests/test_distributed.py)."""
    from jax.sharding import Mesh, NamedSharding

    from heat3d_tpu.ops.halo_pallas import exchange_axis_dma
    from heat3d_tpu.parallel.halo import exchange_axis

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    # 4 cells/shard on the ring axis: admits width 4 (the deep-tb slab)
    base = (32, 32, 32)
    u_host = golden.random_init(base, seed=3)
    for axis in range(3):
        spec = P(*["x" if a == axis else None for a in range(3)])
        u = jax.device_put(jnp.asarray(u_host), NamedSharding(mesh, spec))
        for periodic in (True, False):
            for width in (1, 2, 3, 4):
                got = jax.jit(
                    shard_map(
                        lambda x: exchange_axis_dma(
                            x, axis, "x", 8, ("x",), periodic, 1.5,
                            width=width, interpret=True,
                        ),
                        mesh=mesh, in_specs=spec, out_specs=spec,
                        check_vma=False,
                    )
                )(u)
                want = jax.jit(
                    shard_map(
                        lambda x: exchange_axis(
                            x, axis, "x", 8, periodic, 1.5, width=width
                        ),
                        mesh=mesh, in_specs=spec, out_specs=spec,
                        check_vma=False,
                    )
                )(u)
                np.testing.assert_array_equal(
                    np.asarray(got), np.asarray(want),
                    err_msg=f"axis={axis} periodic={periodic} width={width}",
                )
    print("dma_halo_ring_interpret OK (axes 0-2, widths 1-4)")


def check_fused_dma_overlap_ring_interpret():
    """Fused DMA-overlap step (remote face copies issued at grid step 0,
    interior sweep while in flight, boundary planes after the waits —
    SURVEY.md §7.1 item 7) on a real 8-device ring == the single-device
    oracle, both BCs, single- and multi-chunk-column modes. Runs on a 1D
    named mesh for the same jax-0.9 interpret-mode reason as
    check_dma_halo_ring_interpret; the production 3-axis-mesh dispatch is
    covered by the TPU cross-lowering tests (tests/test_dma_fused.py)."""
    from jax.sharding import Mesh, NamedSharding

    import heat3d_tpu.ops.stencil_dma_fused as fused_mod
    from heat3d_tpu.core.config import GridConfig
    from heat3d_tpu.ops.stencil_jnp import step_single_device

    grid = (16, 16, 16)
    gc = GridConfig(shape=grid)
    u_host = golden.random_init(grid, seed=31)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    spec = P("x")
    orig_chunk = fused_mod.choose_chunk
    # One matrix over {stencil} x {precision tier} x {chunk mode} x {BC}:
    # 27pt exercises the ghost-plane FRAMES (its x-plane taps read them —
    # the x-slab-has-no-corner-neighbors property the widened gate rests
    # on); fp32 matches to FMA rounding; bf16 storage / fp32 compute (the
    # judged config-5 flavor, 2-byte itemsize exercising the ghost-row
    # loads and ring tiles at bf16 geometry) matches to 1 bf16 ulp (2^-8)
    # — kernel vs jnp accumulate in different association orders before
    # the one storage-dtype round-off.
    tiers = [
        (jnp.asarray(u_host), Precision(), 1e-6),
        (jnp.asarray(u_host).astype(jnp.bfloat16), Precision.bf16(), 4e-3),
    ]
    try:
        for kind in ("7pt", "27pt"):
            taps = stencil_taps(
                STENCILS[kind], gc.alpha, gc.effective_dt(), gc.spacing
            )
            for u_in, prec, tol in tiers:
                u_dev = jax.device_put(u_in, NamedSharding(mesh, spec))
                for by in (None, 8):  # None = real chooser; 8 = 2 chunks
                    fused_mod.choose_chunk = (
                        orig_chunk if by is None
                        else lambda *a, _by=by, **k: _by
                    )
                    for bc, bcv in [
                        (BoundaryCondition.DIRICHLET, 1.5),
                        (BoundaryCondition.PERIODIC, 0.0),
                    ]:
                        got = jax.jit(
                            shard_map(
                                lambda x, t=taps,
                                p=bc is BoundaryCondition.PERIODIC,
                                v=bcv: fused_mod.apply_step_fused_dma(
                                    x, t, axis_name="x", axis_size=8,
                                    mesh_axes=("x",), periodic=p,
                                    bc_value=v, interpret=True,
                                ),
                                mesh=mesh, in_specs=spec, out_specs=spec,
                                check_vma=False,
                            )
                        )(u_dev)
                        want = step_single_device(
                            u_in, taps, bc, bcv, precision=prec
                        )
                        assert got.dtype == jnp.dtype(prec.storage)
                        assert want.dtype == jnp.dtype(prec.storage)
                        np.testing.assert_allclose(
                            np.asarray(got.astype(jnp.float32)),
                            np.asarray(want.astype(jnp.float32)),
                            rtol=tol, atol=tol,
                            err_msg=(
                                f"{kind} dtype={prec.storage} by={by} "
                                f"bc={bc}"
                            ),
                        )
    finally:
        fused_mod.choose_chunk = orig_chunk
    print(
        "fused_dma_overlap_ring_interpret OK "
        "(7pt+27pt, fp32+bf16, single+multi chunk, both BCs)"
    )


def check_fused_dma2_superstep_ring_interpret():
    """The tb=2 fused DMA-overlap superstep (width-2 slab RDMA under the
    phase-A sweep, epilogue recomputes the boundary mids) on a real
    8-device ring == TWO single-device oracle steps — the same
    mid-through-storage-dtype round trip as the unfused superstep. Same
    1D-mesh interpret-mode scope as the other DMA tiers."""
    from jax.sharding import Mesh, NamedSharding

    import heat3d_tpu.ops.stencil_dma_fused as fused_mod
    from heat3d_tpu.core.config import GridConfig
    from heat3d_tpu.ops.stencil_jnp import step_single_device

    grid = (32, 16, 16)  # 4 x-planes/shard: the tb=2 kernel's minimum
    gc = GridConfig(shape=grid)
    u_host = golden.random_init(grid, seed=41)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    spec = P("x")
    orig_chunk = fused_mod.choose_chunk
    tiers = [
        (jnp.asarray(u_host), Precision(), 1e-6,
         [(BoundaryCondition.DIRICHLET, 1.5),
          (BoundaryCondition.PERIODIC, 0.0)]),
        # bf16: the mid's bf16 storage round trip must match two unfused
        # bf16 steps; 2 chained updates => 2 bf16 ulps
        (jnp.asarray(u_host).astype(jnp.bfloat16), Precision.bf16(), 8e-3,
         [(BoundaryCondition.DIRICHLET, 1.5)]),
    ]
    try:
        for kind in ("7pt", "27pt"):
            taps = stencil_taps(
                STENCILS[kind], gc.alpha, gc.effective_dt(), gc.spacing
            )
            for u_in, prec, tol, bcs in tiers:
                u_dev = jax.device_put(u_in, NamedSharding(mesh, spec))
                for by in (None, 8):
                    fused_mod.choose_chunk = (
                        orig_chunk if by is None
                        else lambda *a, _by=by, **k: _by
                    )
                    for bc, bcv in bcs:
                        got = jax.jit(
                            shard_map(
                                lambda x, t=taps,
                                p=bc is BoundaryCondition.PERIODIC,
                                v=bcv: fused_mod.apply_superstep_fused_dma(
                                    x, t, axis_name="x", axis_size=8,
                                    mesh_axes=("x",), periodic=p,
                                    bc_value=v, interpret=True,
                                ),
                                mesh=mesh, in_specs=spec, out_specs=spec,
                                check_vma=False,
                            )
                        )(u_dev)
                        want = step_single_device(
                            step_single_device(
                                u_in, taps, bc, bcv, precision=prec
                            ),
                            taps, bc, bcv, precision=prec,
                        )
                        assert got.dtype == jnp.dtype(prec.storage)
                        np.testing.assert_allclose(
                            np.asarray(got.astype(jnp.float32)),
                            np.asarray(want.astype(jnp.float32)),
                            rtol=tol, atol=tol,
                            err_msg=(
                                f"tb2 {kind} dtype={prec.storage} "
                                f"by={by} bc={bc}"
                            ),
                        )
    finally:
        fused_mod.choose_chunk = orig_chunk
    print(
        "fused_dma2_superstep_ring_interpret OK "
        "(7pt+27pt, fp32+bf16, single+multi chunk)"
    )


def check_fused_dma_ghost_outputs_ring_interpret():
    """apply_step_fused_dma(return_ghosts=True) on the 8-device ring: the
    step output still matches the oracle, and the landed ghost planes are
    exactly the neighbor faces the RDMA ring delivers (torus wrap — the
    transfer always runs; Dirichlet substitution happens at READ time,
    in-kernel and in the 3D route's glue)."""
    from jax.sharding import Mesh, NamedSharding

    import heat3d_tpu.ops.stencil_dma_fused as fused_mod
    from heat3d_tpu.core.config import GridConfig
    from heat3d_tpu.ops.stencil_jnp import step_single_device

    grid = (16, 16, 16)
    gc = GridConfig(shape=grid)
    taps = stencil_taps(STENCILS["7pt"], gc.alpha, gc.effective_dt(), gc.spacing)
    u_host = golden.random_init(grid, seed=53)
    u = jnp.asarray(u_host)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    spec = P("x")
    u_dev = jax.device_put(u, NamedSharding(mesh, spec))
    bc, bcv = BoundaryCondition.DIRICHLET, 1.5
    out, glo, ghi = jax.jit(
        shard_map(
            lambda x: fused_mod.apply_step_fused_dma(
                x, taps, axis_name="x", axis_size=8, mesh_axes=("x",),
                periodic=False, bc_value=bcv, interpret=True,
                return_ghosts=True,
            ),
            mesh=mesh, in_specs=spec,
            out_specs=(spec, spec, spec), check_vma=False,
        )
    )(u_dev)
    want = step_single_device(u, taps, bc, bcv)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=1e-6, atol=1e-6
    )
    # shard i's ghosts: glo = global plane (2i-1) mod 16, ghi = plane
    # (2i+2) mod 16 (nx=2 per shard, ring wrap)
    nxl = grid[0] // 8
    glo_g = np.asarray(glo).reshape(8, grid[1], grid[2])
    ghi_g = np.asarray(ghi).reshape(8, grid[1], grid[2])
    for i in range(8):
        np.testing.assert_array_equal(
            glo_g[i], u_host[(i * nxl - 1) % grid[0]]
        )
        np.testing.assert_array_equal(
            ghi_g[i], u_host[((i + 1) * nxl) % grid[0]]
        )
    print("fused_dma_ghost_outputs_ring_interpret OK")


def check_fused_dma_3d_glue():
    """The 3D fused-DMA route's glue (parallel/step._local_step_fused_dma_3d:
    landed-ghost reuse as x faces, axis-ordered y/z face completion via
    exchange_halo_faces(x_ghosts=...), y/z shell patches) on REAL
    x-sharded block meshes == the single-device oracle — with the kernel
    replaced by its XLA reference contract (reference_fused_step_xla).
    Covers 7pt+27pt (corner propagation through the seeded faces),
    both BCs, fp32 + bf16-storage/fp32-compute, meshes (2,2,2)/(2,4,1)/
    (2,1,4)."""
    from heat3d_tpu.ops.stencil_dma_fused import reference_fused_step_xla
    from heat3d_tpu.ops.stencil_jnp import step_single_device
    from heat3d_tpu.parallel.step import _local_step_fused_dma_3d

    grid = (8, 16, 16)
    gc = GridConfig(shape=grid)
    u_host = golden.random_init(grid, seed=61)
    tiers = [
        (jnp.asarray(u_host), Precision(), 1e-6),
        (jnp.asarray(u_host).astype(jnp.bfloat16), Precision.bf16(), 4e-3),
    ]
    for mesh_shape in [(2, 2, 2), (2, 4, 1), (2, 1, 4)]:
        for kind in ("7pt", "27pt"):
            taps = stencil_taps(
                STENCILS[kind], gc.alpha, gc.effective_dt(), gc.spacing
            )
            for u_in, prec, tol in tiers:
                for bc, bcv in [
                    (BoundaryCondition.DIRICHLET, 1.5),
                    (BoundaryCondition.PERIODIC, 0.0),
                ]:
                    cfg = SolverConfig(
                        grid=GridConfig(shape=grid),
                        stencil=StencilConfig(kind=kind, bc=bc, bc_value=bcv),
                        mesh=MeshConfig(shape=mesh_shape),
                        precision=prec,
                        backend="jnp",
                        halo="dma",
                        overlap=True,
                    )
                    mesh = build_mesh(cfg.mesh)
                    sharding = field_sharding(mesh, cfg.mesh)
                    u_dev = jax.device_put(u_in, sharding)
                    spec = P(*cfg.mesh.axis_names)
                    got = jax.jit(
                        shard_map(
                            lambda x, t=taps, c=cfg:
                            _local_step_fused_dma_3d(
                                x, t, c, reference_fused_step_xla
                            ),
                            mesh=mesh, in_specs=spec, out_specs=spec,
                            check_vma=False,
                        )
                    )(u_dev)
                    want = step_single_device(
                        u_in, taps, bc, bcv, precision=prec
                    )
                    assert got.dtype == jnp.dtype(prec.storage)
                    np.testing.assert_allclose(
                        np.asarray(got.astype(jnp.float32)),
                        np.asarray(want.astype(jnp.float32)),
                        rtol=tol, atol=tol,
                        err_msg=(
                            f"3d-glue {kind} mesh={mesh_shape} bc={bc} "
                            f"dtype={prec.storage}"
                        ),
                    )
    print(
        "fused_dma_3d_glue OK (7pt+27pt, fp32+bf16, both BCs, "
        "(2,2,2)/(2,4,1)/(2,1,4))"
    )


def check_fused_dma_edge_size_stress():
    """Edge-size/chunk stress matrix for the fused DMA-overlap kernels on
    the 8-ring (VERDICT r4 item 6): the smallest legal shard depths
    (nx=2 for tb=1, nx=4 for tb=2 — where the overlap window degenerates
    and the epilogue re-streams most of the shard), a non-power-of-two
    chunk split (ny=24 with by=8 -> 3 chunk columns), and the judged
    bf16-storage/fp32-compute tier, all against the single-device
    oracle."""
    from jax.sharding import Mesh, NamedSharding

    import heat3d_tpu.ops.stencil_dma_fused as fused_mod
    from heat3d_tpu.core.config import GridConfig
    from heat3d_tpu.ops.stencil_jnp import step_single_device

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    spec = P("x")
    orig_chunk = fused_mod.choose_chunk
    cases = [
        # (grid, tb, by, storage) — nx/shard = grid[0]//8
        ((16, 24, 16), 1, 8, "fp32"),   # nx=2 minimum, 3 chunk columns
        ((16, 24, 16), 1, None, "bf16"),  # nx=2, bf16 geometry
        ((32, 24, 16), 2, 8, "fp32"),   # nx=4 tb=2 minimum, 3 chunks
        ((32, 24, 16), 2, None, "bf16"),
    ]
    bc, bcv = BoundaryCondition.DIRICHLET, 1.5
    try:
        for grid, tb, by, storage in cases:
            gc = GridConfig(shape=grid)
            taps = stencil_taps(
                STENCILS["7pt"], gc.alpha, gc.effective_dt(), gc.spacing
            )
            u_host = golden.random_init(grid, seed=67)
            prec = Precision() if storage == "fp32" else Precision.bf16()
            tol = 1e-6 if storage == "fp32" else (4e-3 if tb == 1 else 8e-3)
            u_in = jnp.asarray(u_host).astype(jnp.dtype(prec.storage))
            fused_mod.choose_chunk = (
                orig_chunk if by is None else lambda *a, _by=by, **k: _by
            )
            apply = (
                fused_mod.apply_step_fused_dma
                if tb == 1
                else fused_mod.apply_superstep_fused_dma
            )
            u_dev = jax.device_put(u_in, NamedSharding(mesh, spec))
            got = jax.jit(
                shard_map(
                    lambda x, t=taps, f=apply: f(
                        x, t, axis_name="x", axis_size=8, mesh_axes=("x",),
                        periodic=False, bc_value=bcv, interpret=True,
                    ),
                    mesh=mesh, in_specs=spec, out_specs=spec,
                    check_vma=False,
                )
            )(u_dev)
            want = u_in
            for _ in range(tb):
                want = step_single_device(want, taps, bc, bcv, precision=prec)
            np.testing.assert_allclose(
                np.asarray(got.astype(jnp.float32)),
                np.asarray(want.astype(jnp.float32)),
                rtol=tol, atol=tol,
                err_msg=f"stress grid={grid} tb={tb} by={by} {storage}",
            )
    finally:
        fused_mod.choose_chunk = orig_chunk
    print("fused_dma_edge_size_stress OK (nx=2/4, 3-chunk, bf16 tiers)")


def check_sharded_checkpoint_roundtrip():
    import tempfile

    from heat3d_tpu.utils import checkpoint as ckpt

    mesh_cfg = MeshConfig(shape=(2, 2, 2))
    mesh = build_mesh(mesh_cfg)
    sharding = field_sharding(mesh, mesh_cfg)
    u_host = golden.random_init((8, 8, 8), seed=3)
    u = jax.device_put(jnp.asarray(u_host), sharding)
    with tempfile.TemporaryDirectory() as d:
        ckpt.save(d, u, step=42)
        u2, step, _ = ckpt.load(d, sharding)
        assert step == 42
        np.testing.assert_array_equal(np.asarray(u2), np.asarray(u))
    print("sharded_checkpoint_roundtrip OK")


def check_gather_slice_distributed():
    """gather_slice on a real (2,2,2) mesh == the golden field's plane,
    including an uneven (bc-padded) decomposition whose padding must be
    stripped from the plane."""
    from heat3d_tpu.models.heat3d import HeatSolver3D

    for grid in ((8, 8, 8), (10, 9, 8)):
        cfg = SolverConfig(
            grid=GridConfig(shape=grid),
            mesh=MeshConfig(shape=(2, 2, 2)),
            backend="jnp",
        )
        solver = HeatSolver3D(cfg)
        u = solver.run(solver.init_state("gaussian"), 2)
        full = solver.gather(u)
        for axis, index in ((0, 0), (1, grid[1] - 1), (2, grid[2] // 2)):
            plane = solver.gather_slice(u, axis, index)
            idx = tuple(index if a == axis else slice(None) for a in range(3))
            np.testing.assert_array_equal(plane, full[idx])
    print("gather_slice_distributed OK")


def check_deep_tb_tier1():
    """Tier-1 deep-tb certification on REAL multi-device meshes: the k=3
    and k=4 supersteps (jnp ring-recompute path — the route every
    non-TPU platform runs) match k sequential ``make_step_fn`` steps,
    AND both match the fp64 NumPy golden oracle, with cross-device
    width-k ppermutes and 2-then-1-ring mid fills actually executing.
    Focused and fast so test_multidevice.py can run it UNMARKED (tier-1)
    in a 4-device subprocess."""
    import dataclasses

    from heat3d_tpu.models.heat3d import HeatSolver3D

    for k, steps, grid, mesh_shape, bc, bcv in (
        (3, 6, (8, 8, 8), (2, 2, 1), BoundaryCondition.DIRICHLET, 0.5),
        (3, 3, (12, 8, 8), (4, 1, 1), BoundaryCondition.PERIODIC, 0.0),
        (4, 5, (8, 8, 8), (2, 2, 1), BoundaryCondition.DIRICHLET, 0.0),
    ):
        cfg = SolverConfig(
            grid=GridConfig(shape=grid),
            stencil=StencilConfig(bc=bc, bc_value=bcv),
            mesh=MeshConfig(shape=mesh_shape),
            backend="jnp",
        )
        cfgk = dataclasses.replace(cfg, time_blocking=k)
        u_host = golden.random_init(grid, seed=23)
        s1, sk = HeatSolver3D(cfg), HeatSolver3D(cfgk)
        got = sk.gather(sk.run(sk.init_state(u_host), steps))
        want = s1.gather(s1.run(s1.init_state(u_host), steps))
        label = f"k={k} mesh={mesh_shape} bc={bc}"
        np.testing.assert_allclose(
            got, want, rtol=1e-6, atol=1e-6,
            err_msg=f"superstep != sequential steps ({label})",
        )
        want64 = golden.run(
            u_host.astype(np.float64), cfg.grid, cfg.stencil, steps
        )
        np.testing.assert_allclose(
            got, want64, rtol=1e-4, atol=1e-5,
            err_msg=f"superstep != fp64 golden ({label})",
        )
    print("deep_tb_tier1 OK")


def check_deep_tb_streamk_interpret():
    """The fused k-sweep streamk kernel on REAL multi-device meshes via
    the interpret tier: the kernel's domain-edge detection (axis_index
    gating in _pin_out_of_domain) must pin intermediate rings ONLY at
    domain-edge shards and leave exchanged-ghost rings intact at interior
    shards — a (1,1,1) mesh can't tell those apart (every boundary is a
    domain edge there). Nonzero Dirichlet bc_value makes a wrong interior
    pin numerically loud. Parity target: k sequential jnp steps."""
    import dataclasses
    import os

    from heat3d_tpu.models.heat3d import HeatSolver3D
    from heat3d_tpu.parallel.step import _fused_streamk_fn

    saved = {
        k: os.environ.get(k)
        for k in ("HEAT3D_DIRECT_INTERPRET", "HEAT3D_NO_DIRECT")
    }
    os.environ["HEAT3D_DIRECT_INTERPRET"] = "1"
    os.environ["HEAT3D_NO_DIRECT"] = "1"  # pin the streamk route
    try:
        for k, grid, mesh_shape, bc, bcv in (
            (3, (12, 8, 8), (4, 1, 1), BoundaryCondition.DIRICHLET, 0.5),
            (4, (8, 8, 8), (2, 2, 1), BoundaryCondition.DIRICHLET, 0.25),
            (3, (12, 8, 8), (4, 1, 1), BoundaryCondition.PERIODIC, 0.0),
        ):
            cfgk = SolverConfig(
                grid=GridConfig(shape=grid),
                stencil=StencilConfig(bc=bc, bc_value=bcv),
                mesh=MeshConfig(shape=mesh_shape),
                backend="auto",
                time_blocking=k,
            )
            assert _fused_streamk_fn(cfgk) is not None, (
                f"streamk did not resolve under interpret (k={k})"
            )
            cfg1 = dataclasses.replace(
                cfgk, time_blocking=1, backend="jnp"
            )
            u_host = golden.random_init(grid, seed=29)
            sk, s1 = HeatSolver3D(cfgk), HeatSolver3D(cfg1)
            got = sk.gather(sk.run(sk.init_state(u_host), k))
            want = s1.gather(s1.run(s1.init_state(u_host), k))
            np.testing.assert_allclose(
                got, want, rtol=1e-6, atol=1e-6,
                err_msg=(
                    f"streamk superstep != sequential steps "
                    f"(k={k} mesh={mesh_shape} bc={bc})"
                ),
            )
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
    print("deep_tb_streamk_interpret OK")


def _run_solver(cfg, u_host, steps):
    from heat3d_tpu.models.heat3d import HeatSolver3D

    s = HeatSolver3D(cfg)
    return s.gather(s.run(s.init_state(u_host), steps))


def check_plan_bitwise_parity():
    """Plan-built step/superstep programs are BITWISE-identical to the
    ad-hoc exchange path (HEAT3D_NO_PLAN=1 — the pre-plan dispatch kept
    verbatim) on real multi-device meshes, across stencils, temporal
    blocking depths and both halo orderings — the tentpole acceptance
    criterion of the persistent-exchange-plan refactor."""
    import dataclasses
    import os

    from heat3d_tpu.parallel import plan as hplan

    grid = (16, 16, 16)
    u_host = golden.random_init(grid, seed=31)
    combos = [
        ("7pt", 1, "axis", (4, 1, 1)),
        ("7pt", 1, "pairwise", (2, 2, 1)),
        ("7pt", 2, "axis", (2, 2, 1)),
        ("7pt", 3, "axis", (4, 1, 1)),
        ("7pt", 4, "axis", (2, 2, 1)),
        ("27pt", 1, "axis", (2, 2, 1)),
        ("27pt", 2, "axis", (4, 1, 1)),
    ]
    for kind, tb, ho, mesh_shape in combos:
        cfg = SolverConfig(
            grid=GridConfig(shape=grid),
            stencil=StencilConfig(kind=kind, bc_value=0.5),
            mesh=MeshConfig(shape=mesh_shape),
            backend="jnp",
            time_blocking=tb,
            halo_order=ho,
        )
        steps = max(3, tb + 1)
        hplan.clear_plan_cache()
        got = _run_solver(cfg, u_host, steps)
        os.environ["HEAT3D_NO_PLAN"] = "1"
        try:
            want = _run_solver(cfg, u_host, steps)
        finally:
            del os.environ["HEAT3D_NO_PLAN"]
        assert np.array_equal(got, want), (
            f"plan-built program != ad-hoc exchange path bitwise "
            f"({kind} tb={tb} {ho} mesh={mesh_shape})"
        )
    print("plan_bitwise_parity OK")


def check_plan_partitioned_identity():
    """halo_plan='partitioned' (early-bird sub-block sends) is VALUE-
    (indeed bitwise-) identical to 'monolithic' on every judged shape,
    including the uneven decomposition whose padded shards exercise the
    bc-pin masks, pairwise ordering, deep temporal blocking, and
    periodic wrap rings. The partition granularity floor is zeroed so
    the 16^3 faces genuinely split into sub-block permutes (the default
    1 MiB floor would ship them whole)."""
    import dataclasses
    import os

    from heat3d_tpu.parallel import plan as hplan

    os.environ[hplan.ENV_PART_MIN_BYTES] = "0"
    hplan.clear_plan_cache()
    combos = [
        ((16, 16, 16), "7pt", 1, "axis", (4, 1, 1), "dirichlet", 0.5),
        ((18, 18, 18), "7pt", 1, "axis", (4, 1, 1), "dirichlet", 0.25),
        ((16, 16, 16), "27pt", 1, "axis", (2, 2, 1), "dirichlet", 0.0),
        ((16, 16, 16), "7pt", 3, "axis", (2, 2, 1), "dirichlet", 0.5),
        ((16, 16, 16), "7pt", 1, "pairwise", (4, 1, 1), "dirichlet", 0.0),
        ((16, 16, 16), "7pt", 2, "axis", (4, 1, 1), "periodic", 0.0),
    ]
    for grid, kind, tb, ho, mesh_shape, bc, bcv in combos:
        base = SolverConfig(
            grid=GridConfig(shape=grid),
            stencil=StencilConfig(
                kind=kind, bc=BoundaryCondition(bc), bc_value=bcv
            ),
            mesh=MeshConfig(shape=mesh_shape),
            backend="jnp",
            time_blocking=tb,
            halo_order=ho,
        )
        u_host = golden.random_init(grid, seed=37)
        steps = max(3, tb + 1)
        mono = _run_solver(
            dataclasses.replace(base, halo_plan="monolithic"), u_host, steps
        )
        part = _run_solver(
            dataclasses.replace(base, halo_plan="partitioned"), u_host, steps
        )
        assert np.array_equal(mono, part), (
            f"partitioned != monolithic ({grid} {kind} tb={tb} {ho} "
            f"mesh={mesh_shape} bc={bc})"
        )
    del os.environ[hplan.ENV_PART_MIN_BYTES]
    print("plan_partitioned_identity OK")


def check_plan_ensemble_parity():
    """The serve ensemble's traced-bind path consumes plans too: the
    batched run program is bitwise-identical to the ad-hoc exchange
    build (HEAT3D_NO_PLAN=1), and partitioned plans are member-wise
    bitwise-identical to monolithic — on the hybrid b=2 x (2,1,1) mesh,
    where the spatial ring and the batch axis coexist. Granularity
    floor zeroed so the partitioned arm genuinely splits faces."""
    import dataclasses
    import os

    from heat3d_tpu.parallel import plan as hplan

    os.environ[hplan.ENV_PART_MIN_BYTES] = "0"
    hplan.clear_plan_cache()
    from heat3d_tpu.serve.ensemble import EnsembleSolver
    from heat3d_tpu.serve.scenario import Scenario, ScenarioBatch

    def run_ensemble(halo_plan):
        base = SolverConfig(
            grid=GridConfig.cube(16),
            mesh=MeshConfig(shape=(2, 1, 1)),
            backend="jnp",
            time_blocking=2,
            halo_plan=halo_plan,
        )
        batch = ScenarioBatch(
            base,
            [
                Scenario(alpha=0.3, bc_value=1.0, steps=5),
                Scenario(alpha=0.5, steps=7),
            ],
        )
        es = EnsembleSolver(batch, batch_mesh=2)
        return es.gather(es.run(es.init_state(), None))

    got = run_ensemble("monolithic")
    os.environ["HEAT3D_NO_PLAN"] = "1"
    try:
        want = run_ensemble("monolithic")
    finally:
        del os.environ["HEAT3D_NO_PLAN"]
    assert np.array_equal(got, want), (
        "ensemble plan-built run != ad-hoc exchange build bitwise"
    )
    part = run_ensemble("partitioned")
    assert np.array_equal(got, part), (
        "ensemble partitioned != monolithic member-wise"
    )
    del os.environ[hplan.ENV_PART_MIN_BYTES]
    print("plan_ensemble_parity OK")


def check_eqn_heat_spec_vs_legacy_bitwise():
    """The declarative equation frontend reproduces the legacy hardcoded
    heat path BITWISE on real multi-device meshes — the eqn tentpole
    acceptance criterion. The default run compiles the heat spec
    (eqn.solver_taps); the reference arm (HEAT3D_EQN_LEGACY=1) runs the
    verbatim pre-spec stencil_taps derivation. Arms span 7pt/27pt x
    tb{1,2} x axis/pairwise x monolithic/partitioned plans (the
    partition floor zeroed so sub-block permutes genuinely issue)."""
    import os

    from heat3d_tpu.parallel import plan as hplan

    grid = (16, 16, 16)
    u_host = golden.random_init(grid, seed=41)
    combos = [
        ("7pt", 1, "axis", "monolithic", (4, 1, 1)),
        ("7pt", 1, "pairwise", "monolithic", (2, 2, 1)),
        ("7pt", 1, "axis", "partitioned", (2, 2, 1)),
        ("7pt", 2, "axis", "monolithic", (2, 2, 1)),
        ("7pt", 2, "axis", "partitioned", (4, 1, 1)),
        ("27pt", 1, "axis", "monolithic", (2, 2, 1)),
        ("27pt", 1, "axis", "partitioned", (4, 1, 1)),
        ("27pt", 2, "axis", "monolithic", (4, 1, 1)),
    ]
    os.environ[hplan.ENV_PART_MIN_BYTES] = "0"
    try:
        for kind, tb, ho, hp, mesh_shape in combos:
            cfg = SolverConfig(
                grid=GridConfig(shape=grid),
                stencil=StencilConfig(kind=kind, bc_value=0.5),
                mesh=MeshConfig(shape=mesh_shape),
                backend="jnp",
                time_blocking=tb,
                halo_order=ho,
                halo_plan=hp,
                equation="heat",
            )
            steps = max(3, tb + 1)
            hplan.clear_plan_cache()
            got = _run_solver(cfg, u_host, steps)
            os.environ["HEAT3D_EQN_LEGACY"] = "1"
            try:
                want = _run_solver(cfg, u_host, steps)
            finally:
                del os.environ["HEAT3D_EQN_LEGACY"]
            assert np.array_equal(got, want), (
                f"spec-compiled heat != legacy hardcoded path bitwise "
                f"({kind} tb={tb} {ho} {hp} mesh={mesh_shape})"
            )
    finally:
        del os.environ[hplan.ENV_PART_MIN_BYTES]
    print("eqn_heat_spec_vs_legacy_bitwise OK")


def check_eqn_families_golden_distributed():
    """Every spec-built family advances correctly end-to-end on a real
    4-device mesh: the distributed fp32 run matches the fp64 golden
    stepper driven with the SAME spec-compiled taps (machinery parity —
    halo plans, supersteps, padding pins all carrying the new taps), and
    the periodic plane-wave arm tracks the family's analytic MMS
    solution. One arm runs the auto knobs (halo='auto',
    time_blocking=0) so tuner resolution of an eqn config is exercised,
    and one runs a partitioned plan."""
    import dataclasses

    from heat3d_tpu import eqn

    grid = (16, 16, 16)
    # (family, params, tb, plan, mesh, dt) — reaction combos pass an
    # explicit dt: their decay rates tighten the explicit-Euler bound
    # below the default diffusion-only derivation, which config
    # validation now (correctly) rejects for non-heat families
    combos = [
        ("aniso-diffusion", (), 1, "monolithic", (2, 2, 1), None),
        ("advection-diffusion", (("vx", 0.8), ("vy", 0.4)), 1,
         "partitioned", (4, 1, 1), None),
        ("advection-diffusion", (), 2, "monolithic", (2, 2, 1), None),
        ("reaction-diffusion", (("rate", -0.7),), 1, "monolithic",
         (4, 1, 1), 0.3),
        ("reaction-diffusion", (), 2, "partitioned", (2, 2, 1), 0.3),
    ]
    import os

    from heat3d_tpu.parallel import plan as hplan

    os.environ[hplan.ENV_PART_MIN_BYTES] = "0"
    try:
        for fam, params, tb, hp, mesh_shape, dt in combos:
            cfg = SolverConfig(
                grid=GridConfig(shape=grid, alpha=0.4, dt=dt),
                stencil=StencilConfig(kind="7pt", bc_value=0.25),
                mesh=MeshConfig(shape=mesh_shape),
                backend="jnp",
                time_blocking=tb,
                halo_plan=hp,
                equation=fam,
                eq_params=params,
            )
            hplan.clear_plan_cache()
            u_host = golden.random_init(grid, seed=43)
            steps = 6
            got = _run_solver(cfg, u_host, steps).astype(np.float64)
            want = golden.run(
                u_host, cfg.grid, cfg.stencil, steps,
                taps=eqn.solver_taps(cfg),
            )
            rel = np.max(np.abs(got - want)) / max(np.max(np.abs(want)), 1e-30)
            assert rel < 1e-5, (
                f"{fam} tb={tb} {hp} mesh={mesh_shape}: distributed run "
                f"diverges from the fp64 golden oracle (rel {rel:.2e})"
            )
    finally:
        del os.environ[hplan.ENV_PART_MIN_BYTES]

    # tuner-resolution arm: auto knobs on an eqn config resolve through
    # the cache (miss -> static fallback) and the run still matches gold
    cfg = SolverConfig(
        grid=GridConfig(shape=grid, alpha=0.4),
        stencil=StencilConfig(kind="7pt"),
        mesh=MeshConfig(shape=(2, 2, 1)),
        backend="jnp",
        halo="auto",
        time_blocking=0,
        equation="advection-diffusion",
    )
    u_host = golden.random_init(grid, seed=44)
    got = _run_solver(cfg, u_host, 5).astype(np.float64)
    resolved = dataclasses.replace(cfg, halo="ppermute", time_blocking=1)
    want = golden.run(
        u_host, resolved.grid, resolved.stencil, 5,
        taps=eqn.solver_taps(resolved),
    )
    rel = np.max(np.abs(got - want)) / max(np.max(np.abs(want)), 1e-30)
    assert rel < 1e-5, f"auto-knob eqn run diverges from gold (rel {rel:.2e})"

    # MMS arm: periodic plane wave vs the analytic solution (loose bound
    # — the convergence-order discipline lives in tests/test_eqn.py; this
    # proves the DISTRIBUTED program tracks the same continuous solution)
    n = 16
    shape = (n, n, n)
    spacing = (1.0 / n, 1.0 / n, 1.0 / n)
    cfg = SolverConfig(
        grid=GridConfig(shape=shape, spacing=spacing, alpha=0.01,
                        dt=2e-4),
        stencil=StencilConfig(kind="7pt", bc=BoundaryCondition.PERIODIC),
        mesh=MeshConfig(shape=(2, 2, 1)),
        backend="jnp",
        equation="advection-diffusion",
        eq_params=(("vx", 0.5), ("vy", 0.25), ("vz", 0.0)),
    )
    wave = (1, 1, 0)
    steps = 50
    t_end = steps * cfg.grid.effective_dt()
    mu, omega = eqn.mms_rates(cfg, golden.wavevector(shape, spacing, wave))
    u0 = golden.plane_wave(shape, spacing, wave)
    got = _run_solver(cfg, u0.astype(np.float32), steps).astype(np.float64)
    want = golden.plane_wave(shape, spacing, wave, t=t_end, mu=mu,
                             omega=omega)
    err = np.max(np.abs(got - want))
    assert err < 5e-2, (
        f"distributed advection-diffusion run does not track the "
        f"analytic plane wave (max err {err:.3e})"
    )
    print("eqn_families_golden_distributed OK")


def check_eqn_serve_traced_bind():
    """Per-member spec coefficients through the serve traced bind: an
    advection-diffusion batch whose members carry DIFFERENT velocities
    (Scenario.eq_params) runs as ONE compiled parametric program on the
    hybrid b=2 x (2,1,1) mesh, and each member matches its own solo
    HeatSolver3D run; the baked certification mode is bitwise-identical
    to the solo runs by construction."""
    from heat3d_tpu.models.heat3d import HeatSolver3D
    from heat3d_tpu.serve.ensemble import EnsembleSolver
    from heat3d_tpu.serve.scenario import Scenario, ScenarioBatch

    base = SolverConfig(
        grid=GridConfig.cube(16, alpha=0.4),
        mesh=MeshConfig(shape=(2, 1, 1)),
        backend="jnp",
        equation="advection-diffusion",
    )
    members = [
        Scenario(alpha=0.4, steps=5, eq_params=(("vx", 0.5),)),
        Scenario(alpha=0.3, steps=5, eq_params=(("vx", 1.0), ("vy", 0.5))),
    ]
    batch = ScenarioBatch(base, members)

    solos = []
    for i in range(len(members)):
        cfg_i = batch.member_config(i)
        s = HeatSolver3D(cfg_i)
        solos.append(s.gather(s.run(s.init_state("hot-cube"), 5)))

    es = EnsembleSolver(batch, batch_mesh=2, bind="traced")
    fields = es.gather(es.run(es.init_state(), None))
    for i, solo in enumerate(solos):
        rel = np.max(np.abs(fields[i].astype(np.float64) - solo)) / max(
            float(np.max(np.abs(solo))), 1e-30
        )
        assert rel < 1e-5, (
            f"traced-bind member {i} (own velocity) diverges from its "
            f"solo run (rel {rel:.2e})"
        )

    es_baked = EnsembleSolver(batch, batch_mesh=1, bind="baked")
    fields_b = es_baked.gather(es_baked.run(es_baked.init_state(), None))
    for i, solo in enumerate(solos):
        assert np.array_equal(fields_b[i], solo.astype(fields_b.dtype)), (
            f"baked member {i} != its solo run bitwise"
        )
    print("eqn_serve_traced_bind OK")


def check_timeint_dist_bitwise():
    """Leapfrog (tb=1 and the tb=2 two-level ring superstep), the
    matrix-free CG solve at 15x the explicit CFL bound, and the
    variable-coefficient flux update all run on a REAL (2,2,1) mesh
    BITWISE-identical to the (1,1,1) solo run. Leapfrog/CG certify at
    f32 storage with f64 compute/residual (the battery env sets
    JAX_ENABLE_X64): at f32 compute XLA:CPU contracts the tap-sweep FMAs
    differently across mesh shapes (1-ulp drift), so bitwise solo==dist
    is the f64-compute tier's contract; the varcoef flux update is
    bitwise even at plain f32."""
    import dataclasses

    from jax.sharding import Mesh, NamedSharding

    from heat3d_tpu import timeint
    from heat3d_tpu.timeint import cg, coeffield

    n = 12
    rng = np.random.default_rng(7)
    prec = Precision(storage="float32", compute="float64",
                     residual="float64")
    mesh_s = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                  ("x", "y", "z"))
    mesh_d = Mesh(np.array(jax.devices()[:4]).reshape(2, 2, 1),
                  ("x", "y", "z"))
    sh_s = NamedSharding(mesh_s, P("x", "y", "z"))
    sh_d = NamedSharding(mesh_d, P("x", "y", "z"))

    # leapfrog: tb=1 (plain steps) and tb=2 (shrinking-ring superstep,
    # the k*r / (k-1)*r two-level ghost plan) — both carry levels bitwise
    for tb in (1, 2):
        cfg = SolverConfig(
            grid=GridConfig(shape=(n, n, n), dt=0.01,
                            spacing=(1 / n, 1 / n, 1 / n)),
            stencil=StencilConfig(kind="7pt",
                                  bc=BoundaryCondition.DIRICHLET,
                                  bc_value=0.1),
            mesh=MeshConfig(shape=(1, 1, 1)),
            backend="jnp",
            halo="ppermute",
            time_blocking=tb,
            equation="wave",
            eq_params=(("c", 1.0),),
            integrator="leapfrog",
            precision=prec,
        )
        cfg_d = dataclasses.replace(cfg, mesh=MeshConfig(shape=(2, 2, 1)))
        u0 = rng.standard_normal((n, n, n)).astype(np.float32)
        um1 = rng.standard_normal((n, n, n)).astype(np.float32)
        ms_s = jax.jit(timeint.make_multistep_fn(cfg, mesh_s))
        ms_d = jax.jit(timeint.make_multistep_fn(cfg_d, mesh_d))
        c_s = ms_s((jax.device_put(u0, sh_s), jax.device_put(um1, sh_s)),
                   jnp.int32(7))
        c_d = ms_d((jax.device_put(u0, sh_d), jax.device_put(um1, sh_d)),
                   jnp.int32(7))
        for lvl in (0, 1):
            assert np.array_equal(np.asarray(c_s[lvl]),
                                  np.asarray(c_d[lvl])), (
                f"leapfrog tb={tb} carry level {lvl}: dist != solo bitwise"
            )

    # implicit CG at 15x CFL: field bitwise AND the psum-replicated
    # convergence decision identical (same iteration count on every mesh)
    cfg_c = SolverConfig(
        grid=GridConfig(shape=(n, n, n), spacing=(1 / n, 1 / n, 1 / n)),
        stencil=StencilConfig(kind="7pt", bc=BoundaryCondition.DIRICHLET,
                              bc_value=0.5),
        mesh=MeshConfig(shape=(1, 1, 1)),
        backend="jnp",
        halo="ppermute",
        integrator="implicit-cg",
        precision=prec,
    )
    cfg_c = dataclasses.replace(
        cfg_c,
        grid=dataclasses.replace(cfg_c.grid,
                                 dt=15 * cfg_c.grid.stable_dt()),
    )
    cfg_cd = dataclasses.replace(cfg_c, mesh=MeshConfig(shape=(2, 2, 1)))
    u0c = rng.uniform(0.0, 1.0, (n, n, n)).astype(np.float32)
    u1s, it_s, rr_s = jax.jit(
        cg.make_step_fn(cfg_c, mesh_s, with_stats=True)
    )(jax.device_put(u0c, sh_s))
    u1d, it_d, _ = jax.jit(
        cg.make_step_fn(cfg_cd, mesh_d, with_stats=True)
    )(jax.device_put(u0c, sh_d))
    assert np.array_equal(np.asarray(u1s), np.asarray(u1d)), (
        "implicit-cg solve: dist != solo bitwise"
    )
    assert int(it_s) == int(it_d) and 1 <= int(it_s) <= 64, (
        f"CG iteration counts differ across meshes "
        f"({int(it_s)} vs {int(it_d)})"
    )
    assert float(rr_s) < 1e-5

    # varcoef flux update: bitwise at plain f32 (one association order)
    cfg_v = SolverConfig(
        grid=GridConfig(shape=(n, n, n), dt=5e-4,
                        spacing=(1 / n, 1 / n, 1 / n)),
        stencil=StencilConfig(kind="7pt", bc=BoundaryCondition.PERIODIC),
        mesh=MeshConfig(shape=(1, 1, 1)),
        backend="jnp",
        halo="ppermute",
    )
    cfg_vd = dataclasses.replace(cfg_v, mesh=MeshConfig(shape=(2, 2, 1)))
    a = coeffield.make_coef_field("checker", (n, n, n),
                                  seed=1).astype(np.float32)
    uv = rng.standard_normal((n, n, n)).astype(np.float32)
    r_s = jax.jit(coeffield.make_varcoef_multistep_fn(cfg_v, mesh_s))(
        jax.device_put(uv, sh_s), jax.device_put(a, sh_s), jnp.int32(5))
    r_d = jax.jit(coeffield.make_varcoef_multistep_fn(cfg_vd, mesh_d))(
        jax.device_put(uv, sh_d), jax.device_put(a, sh_d), jnp.int32(5))
    assert np.array_equal(np.asarray(r_s), np.asarray(r_d)), (
        "varcoef flux update: dist != solo bitwise"
    )
    print("timeint_dist_bitwise OK")


def check_timeint_supervised_two_level_resume():
    """A leapfrog run interrupted at step 4 and resumed to step 8 lands
    BITWISE on the uninterrupted run's final carry — BOTH levels restored
    from the two-level checkpoint generation. A newer generation written
    by a DIFFERENT integrator (single-level explicit-euler) is skipped
    (MultiLevelCheckpointError — wrong shape of state, not corrupt
    shards) WITHOUT being quarantined and stays on disk."""
    import dataclasses
    import os
    import shutil
    import tempfile

    from heat3d_tpu import timeint
    from heat3d_tpu.models.heat3d import HeatSolver3D
    from heat3d_tpu.resilience.supervisor import load_latest_generation

    n = 12
    cfg = SolverConfig(
        grid=GridConfig(shape=(n, n, n), dt=0.01,
                        spacing=(1 / n, 1 / n, 1 / n)),
        stencil=StencilConfig(kind="7pt",
                              bc=BoundaryCondition.DIRICHLET,
                              bc_value=0.1),
        mesh=MeshConfig(shape=(2, 2, 1)),
        backend="jnp",
        halo="ppermute",
        equation="wave",
        eq_params=(("c", 1.0),),
        integrator="leapfrog",
    )
    tmp = tempfile.mkdtemp(prefix="timeint_resume_")
    try:
        root_a = os.path.join(tmp, "a")
        res_a = HeatSolver3D(cfg).run_supervised(
            8, root_a, checkpoint_every=2)
        assert res_a.steps_done == 8 and not res_a.resumed_from

        root_b = os.path.join(tmp, "b")
        res_half = HeatSolver3D(cfg).run_supervised(
            4, root_b, checkpoint_every=2)
        assert res_half.steps_done == 4
        res_b = HeatSolver3D(cfg).run_supervised(
            8, root_b, checkpoint_every=2)
        assert res_b.resumed_from == 4 and res_b.steps_done == 8
        for lvl in (0, 1):
            ga = res_a.solver.gather(res_a.u[lvl])
            gb = res_b.solver.gather(res_b.u[lvl])
            assert np.array_equal(ga, gb), (
                f"resumed carry level {lvl} != uninterrupted run bitwise"
            )

        # a NEWER single-level (explicit-euler) generation must be
        # skipped in place, never quarantined
        cfg_exp = dataclasses.replace(
            cfg, equation="heat", eq_params=(),
            integrator="explicit-euler")
        es = HeatSolver3D(cfg_exp)
        fake = os.path.join(root_b, "gen-00000012")
        es.save_checkpoint(fake, es.init_state("hot-cube"), 12)
        lf = HeatSolver3D(cfg)
        try:
            lf.load_checkpoint(fake)
            raise AssertionError(
                "single-level checkpoint loaded into a two-level carry")
        except timeint.MultiLevelCheckpointError:
            pass
        loaded, quarantined = load_latest_generation(lf, root_b)
        assert loaded is not None, "no generation loaded after skip"
        carry, step = loaded
        assert step == 8, f"expected resume at step 8, got {step}"
        assert quarantined == [], (
            f"level-mismatch generation was quarantined: {quarantined}")
        assert os.path.isdir(fake), "skipped generation must stay on disk"
        assert isinstance(carry, tuple) and len(carry) == 2
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print("timeint_supervised_two_level_resume OK")


def check_timeint_coef_serve_packing():
    """Per-member coefficient fields through the serve traced route on a
    real (2,2,1) spatial mesh: each member of a B=2 coef-field batch
    matches its own fp64 flux-form oracle, a B=1 batch reproduces the
    packed member BITWISE (packing invariance), and the run's halo
    traffic lands in the plan-audit ledger (exchange_plan_built /
    plan_cache_hit) exactly like the solution field's."""
    import json
    import os
    import tempfile

    from heat3d_tpu import obs
    from heat3d_tpu.serve.ensemble import EnsembleSolver
    from heat3d_tpu.serve.scenario import Scenario, ScenarioBatch
    from heat3d_tpu.timeint import coeffield

    base = SolverConfig(
        grid=GridConfig.cube(12),
        mesh=MeshConfig(shape=(2, 2, 1)),
        backend="jnp",
    )
    members = [
        Scenario(init="hot-cube", coef_field=("checker", 0, 0.5, 1.5),
                 bc_value=0.25, steps=5),
        Scenario(init="gaussian", coef_field=("lognormal", 7, 0.3, 2.0),
                 bc_value=0.0, steps=5, seed=1),
    ]
    batch = ScenarioBatch(base, members)
    assert batch.has_coef_fields

    tmp = tempfile.mkdtemp(prefix="timeint_serve_")
    led = os.path.join(tmp, "led.jsonl")
    obs.activate(led)
    try:
        es = EnsembleSolver(batch)
        out = es.gather(es.run(es.init_state()))
    finally:
        obs.deactivate()

    for m in range(2):
        a = batch.member_coef_field(m)
        u_ref = golden.make_init(
            members[m].init, base.grid.shape, seed=members[m].seed
        ).astype(np.float64)
        dt = batch.member_dt(m)
        for _ in range(members[m].steps):
            u_ref = coeffield.reference_varcoef_step(
                u_ref, a, dt, base.grid.spacing, periodic=False,
                bc_value=members[m].bc_value,
            )
        rel = np.max(np.abs(out[m] - u_ref)) / max(
            float(np.max(np.abs(u_ref))), 1e-30)
        assert rel < 1e-5, (
            f"coef-field member {m} diverges from its fp64 flux oracle "
            f"(rel {rel:.2e})")

    for m in range(2):
        b1 = ScenarioBatch(base, [members[m]])
        e1 = EnsembleSolver(b1)
        o1 = e1.gather(e1.run(e1.init_state()))[0]
        assert np.array_equal(o1, out[m]), (
            f"coef-field member {m}: B=1 != packed B=2 bitwise")

    with open(led) as fh:
        evs = [json.loads(line) for line in fh if line.strip()]
    plan_evs = [e for e in evs
                if e.get("event") in ("exchange_plan_built",
                                      "plan_cache_hit")]
    assert plan_evs, "no plan-audit events from the coef-field run"
    print("timeint_coef_serve_packing OK")


def check_fused_rdma_ring_interpret():
    """The fused in-kernel RDMA superstep kernels (plan-scheduled remote
    face copies under the sweep — ops/stencil_fused_rdma.py) on a REAL
    4-device interpret ring, 7pt x dirichlet/periodic x tb{1,2} x
    monolithic/partitioned plans. Three-way contract per case:
    (1) the fused-RDMA kernel is BITWISE-equal to the certified
    fused-DMA kernel — they share the sweep/emit bodies verbatim
    through the rdma_factory seam, so ANY value difference means the
    planned transfer protocol landed different ghost bytes;
    (2) the partitioned plan (genuine sub-blocks, min_part_bytes=0) is
    BITWISE-equal to monolithic — sub-block decomposition is pure
    scheduling, never values;
    (3) both match the single-device unfused oracle at the battery's
    standard fp32 tolerance (1e-6): the fused streaming sweep and the
    padded jnp sweep accumulate in different association orders, the
    same posture as every other kernel battery here — bitwise equality
    vs the UNFUSED route is not a property any fused kernel in this
    repo has or claims."""
    from jax.sharding import Mesh, NamedSharding

    import heat3d_tpu.ops.stencil_dma_fused as dma_mod
    import heat3d_tpu.ops.stencil_fused_rdma as rdma_mod
    from heat3d_tpu.core.config import GridConfig
    from heat3d_tpu.ops.stencil_jnp import step_single_device
    from heat3d_tpu.parallel.plan import build_plan

    grid = (16, 16, 16)  # 4 x-planes/shard on 4 devices: the tb=2 floor
    gc = GridConfig(shape=grid)
    taps = stencil_taps(STENCILS["7pt"], gc.alpha, gc.effective_dt(),
                        gc.spacing)
    u_host = golden.random_init(grid, seed=53)
    u_in = jnp.asarray(u_host)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("x",))
    spec = P("x")
    u_dev = jax.device_put(u_in, NamedSharding(mesh, spec))

    def run(fn, **kw):
        return np.asarray(
            jax.jit(
                shard_map(
                    lambda x: fn(x, taps, **kw),
                    mesh=mesh, in_specs=spec, out_specs=spec,
                    check_vma=False,
                )
            )(u_dev)
        )

    for bc, bcv in [
        (BoundaryCondition.DIRICHLET, 1.5),
        (BoundaryCondition.PERIODIC, 0.0),
    ]:
        for tb, dma_fn, rdma_fn in (
            (1, dma_mod.apply_step_fused_dma,
             rdma_mod.apply_step_fused_rdma),
            (2, dma_mod.apply_superstep_fused_dma,
             rdma_mod.apply_superstep_fused_rdma),
        ):
            kw = dict(
                axis_name="x", axis_size=4, mesh_axes=("x",),
                periodic=bc is BoundaryCondition.PERIODIC,
                bc_value=bcv, interpret=True,
            )
            base = run(dma_fn, **kw)
            by_mode = {}
            for mode in ("monolithic", "partitioned"):
                plan = build_plan(
                    MeshConfig(shape=(4, 1, 1)), bc, width=tb,
                    transport="ppermute", mode=mode, min_part_bytes=0,
                )
                if mode == "partitioned":
                    # the case must exercise GENUINE sub-block sends
                    bounds = rdma_mod.plan_send_bounds(
                        plan, (grid[0] // 4,) + grid[1:], 4
                    )
                    assert len(bounds) > 1, bounds
                by_mode[mode] = run(rdma_fn, plan=plan, **kw)
                assert np.array_equal(by_mode[mode], base), (
                    f"fused-rdma != fused-dma bitwise "
                    f"(tb={tb} bc={bc} plan={mode})"
                )
            assert np.array_equal(
                by_mode["monolithic"], by_mode["partitioned"]
            ), f"partitioned != monolithic bitwise (tb={tb} bc={bc})"
            want = u_in
            for _ in range(tb):
                want = step_single_device(want, taps, bc, bcv)
            np.testing.assert_allclose(
                by_mode["monolithic"], np.asarray(want),
                rtol=1e-6, atol=1e-6,
                err_msg=f"fused-rdma vs unfused oracle (tb={tb} bc={bc})",
            )
    print(
        "fused_rdma_ring_interpret OK "
        "(7pt, both BCs, tb1+tb2, monolithic+partitioned, "
        "bitwise vs fused-dma + oracle)"
    )


def check_fused_rdma_route_dispatch():
    """The fused_rdma route end-to-end through HeatSolver3D on a real
    4-device mesh: with the knob on (and the interpret gate), the step
    and superstep builders must dispatch the fused route (emulation tier
    = the kernel's certified pure-XLA reference contract), phase_programs
    must alias the fused phase to the step program, and the simulated
    values must match the unfused jnp route at the standard tolerance —
    under monolithic AND genuine-sub-block partitioned plans
    (HEAT3D_PLAN_PART_MIN_BYTES=0, keyed into the plan cache)."""
    import dataclasses
    import os

    from heat3d_tpu.models.heat3d import HeatSolver3D, _select_backend
    from heat3d_tpu.parallel.step import (
        PHASE_FUSED,
        PHASE_STEP,
        _fused_rdma2_fn,
        _fused_rdma_fn,
        phase_programs,
    )

    saved = {
        k: os.environ.get(k)
        for k in (
            "HEAT3D_DIRECT_INTERPRET",
            "HEAT3D_FUSED_RDMA",
            "HEAT3D_PLAN_PART_MIN_BYTES",
        )
    }
    os.environ["HEAT3D_DIRECT_INTERPRET"] = "1"
    os.environ.pop("HEAT3D_FUSED_RDMA", None)
    os.environ["HEAT3D_PLAN_PART_MIN_BYTES"] = "0"
    grid = (16, 16, 16)
    try:
        for tb in (1, 2):
            for hp in ("monolithic", "partitioned"):
                cfg = SolverConfig(
                    grid=GridConfig(shape=grid),
                    stencil=StencilConfig(
                        bc=BoundaryCondition.DIRICHLET, bc_value=0.5
                    ),
                    mesh=MeshConfig(shape=(4, 1, 1)),
                    backend="auto",
                    time_blocking=tb,
                    halo_plan=hp,
                    fused_rdma="on",
                )
                route = (
                    _fused_rdma_fn(cfg) if tb == 1 else _fused_rdma2_fn(cfg)
                )
                assert route is not None, (
                    f"fused_rdma route did not resolve (tb={tb} hp={hp})"
                )
                mesh = build_mesh(cfg.mesh)
                progs = phase_programs(cfg, mesh, _select_backend(cfg))
                assert progs[PHASE_FUSED] is progs[PHASE_STEP], (
                    "fused phase must alias the step program"
                )
                cfg_off = dataclasses.replace(
                    cfg, fused_rdma="off", backend="jnp",
                    halo_plan="monolithic", time_blocking=1,
                )
                u_host = golden.random_init(grid, seed=61)
                s_on, s_off = HeatSolver3D(cfg), HeatSolver3D(cfg_off)
                got = s_on.gather(s_on.run(s_on.init_state(u_host), 2))
                want = s_off.gather(s_off.run(s_off.init_state(u_host), 2))
                np.testing.assert_allclose(
                    got, want, rtol=1e-6, atol=1e-6,
                    err_msg=f"fused_rdma route vs jnp (tb={tb} hp={hp})",
                )
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
    print("fused_rdma_route_dispatch OK (tb1+tb2, both plan modes)")


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "eqn":
        # focused tier-1 entry (tests/test_eqn.py runs it unmarked on a
        # 4-device mesh): the declarative-equation acceptance battery —
        # spec-vs-legacy heat bitwise, family golden/MMS e2e, serve
        # traced-bind with per-member spec coefficients
        n = len(jax.devices())
        assert n >= 4, f"expected >= 4 CPU devices, got {n}"
        check_eqn_heat_spec_vs_legacy_bitwise()
        check_eqn_families_golden_distributed()
        check_eqn_serve_traced_bind()
        print("ALL MULTIDEVICE CHECKS PASSED")
        return
    if len(sys.argv) > 1 and sys.argv[1] == "plan":
        # focused tier-1 entry (tests/test_plan.py runs it unmarked on a
        # 4-device mesh): the persistent-exchange-plan acceptance battery
        n = len(jax.devices())
        assert n >= 4, f"expected >= 4 CPU devices, got {n}"
        check_plan_bitwise_parity()
        check_plan_partitioned_identity()
        check_plan_ensemble_parity()
        print("ALL MULTIDEVICE CHECKS PASSED")
        return
    if len(sys.argv) > 1 and sys.argv[1] == "timeint":
        # focused tier-1 entry (tests/test_timeint.py runs it unmarked on
        # a 4-device mesh with JAX_ENABLE_X64=1): the multi-level /
        # implicit integration battery — leapfrog + CG + varcoef
        # dist==solo bitwise, two-level supervised resume with the
        # level-mismatch skip, coef-field serve packing/oracle/plan-audit
        n = len(jax.devices())
        assert n >= 4, f"expected >= 4 CPU devices, got {n}"
        check_timeint_dist_bitwise()
        check_timeint_supervised_two_level_resume()
        check_timeint_coef_serve_packing()
        print("ALL MULTIDEVICE CHECKS PASSED")
        return
    if len(sys.argv) > 1 and sys.argv[1] == "fused_rdma":
        # focused tier-1 entry (tests/test_fused_rdma.py runs it unmarked
        # on a 4-device mesh): the fused in-kernel RDMA superstep battery
        # — kernel bitwise vs the certified fused-DMA bodies + plan-mode
        # bitwise identity + oracle parity, then the solver-route
        # dispatch/aliasing/parity contract
        n = len(jax.devices())
        assert n >= 4, f"expected >= 4 CPU devices, got {n}"
        check_fused_rdma_ring_interpret()
        check_fused_rdma_route_dispatch()
        print("ALL MULTIDEVICE CHECKS PASSED")
        return
    if len(sys.argv) > 1 and sys.argv[1] == "deep_tb":
        # focused tier-1 entry (test_multidevice.py runs it unmarked on a
        # 4-device mesh; the full 8-device battery stays slow-marked)
        n = len(jax.devices())
        assert n >= 4, f"expected >= 4 CPU devices, got {n}"
        check_deep_tb_tier1()
        check_deep_tb_streamk_interpret()
        print("ALL MULTIDEVICE CHECKS PASSED")
        return
    n = len(jax.devices())
    assert n == 8, f"expected 8 CPU devices, got {n} ({jax.devices()})"
    check_step_matches_single_device()
    check_faces_direct_step_distributed()
    check_faces_direct_superstep_distributed()
    check_overlap_step_distributed()
    check_uneven_decomposition()
    check_device_init_distributed()
    check_time_blocking_distributed()
    check_bf16_distributed()
    check_halo_ghost_identity()
    check_multistep_vs_golden()
    check_dma_halo_ring_interpret()
    check_fused_dma_overlap_ring_interpret()
    check_fused_dma2_superstep_ring_interpret()
    check_fused_dma_ghost_outputs_ring_interpret()
    check_fused_dma_3d_glue()
    check_fused_dma_edge_size_stress()
    check_sharded_checkpoint_roundtrip()
    check_gather_slice_distributed()
    print("ALL MULTIDEVICE CHECKS PASSED")


if __name__ == "__main__":
    main()
    sys.exit(0)
