"""IR-level SPMD certification tests (tier-1, CPU): every checker family
fires on a seeded-violation program and stays quiet on the real judged
programs, the shard-varying-predicate collective is caught at the jaxpr
tier where the AST checker is provably blind, fingerprints anchor on
(checker, config-key, invariant) — never jaxpr text — and, the
acceptance gate, `heat3d lint --ir --json` is clean on this repo across
the judged matrix in a fresh multi-device process."""

import dataclasses
import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from heat3d_tpu.analysis import collectives as ast_collectives
from heat3d_tpu.analysis.ir import (
    IR_CHECKERS,
    collectives as irc,
    dtypeflow as ird,
    footprint as irf,
    jaxpr_tools as jt,
    memcontract as irm,
    programs as irp,
)
from heat3d_tpu.core.config import (
    GridConfig,
    MeshConfig,
    Precision,
    SolverConfig,
)
from heat3d_tpu.ops.stencil_jnp import apply_taps_padded, residual_sumsq
from heat3d_tpu.parallel.halo import exchange_halo
from heat3d_tpu.parallel.step import _solver_taps, make_step_fn
from heat3d_tpu.parallel.topology import abstract_mesh
from heat3d_tpu.utils.compat import shard_map

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

SPEC = P("x", "y", "z")


def _cfg(**kw):
    kw.setdefault("grid", GridConfig.cube(16))
    kw.setdefault("mesh", MeshConfig(shape=(2, 1, 1)))
    kw.setdefault("backend", "jnp")
    return SolverConfig(**kw)


def _case(fn, cfg, kind="step", key="seed", aval=None, **kw):
    """A ProgramCase over an ABSTRACT mesh — tracing needs no devices, so
    the in-process tests run multi-chip programs on the 1-CPU pytest
    box exactly like topology.lower_for_mesh does."""
    aval = aval or jax.ShapeDtypeStruct(
        cfg.padded_shape, jnp.dtype(cfg.precision.storage)
    )
    kw.setdefault(
        "mesh_sizes", dict(zip(cfg.mesh.axis_names, cfg.mesh.shape))
    )
    return irp.ProgramCase(
        key=key,
        cfg=cfg,
        kind=kind,
        path="tests/seeded.py",
        fn=fn,
        avals=(aval,),
        **kw,
    )


def _sharded(fn, cfg, out_specs=SPEC):
    return shard_map(
        fn,
        mesh=abstract_mesh(cfg.mesh),
        in_specs=SPEC,
        out_specs=out_specs,
        check_vma=False,
    )


def _codes(findings):
    return sorted({f.code for f in findings})


# ---- collective topology (ANL6xx) -----------------------------------------


def test_clean_judged_programs_have_no_collective_findings():
    """Negative: the real step/superstep builders over both halo
    orderings and a block mesh certify clean."""
    cases = []
    for mesh, tb, order in (
        ((2, 2, 1), 1, "axis"),
        ((2, 1, 1), 1, "pairwise"),
        ((2, 2, 2), 3, "axis"),
    ):
        cfg = _cfg(mesh=MeshConfig(shape=mesh), halo_order=order)
        cfg = dataclasses.replace(cfg, time_blocking=tb)
        from heat3d_tpu.parallel.step import make_superstep_fn

        builder = (
            make_superstep_fn(cfg, abstract_mesh(cfg.mesh))
            if tb > 1
            else make_step_fn(cfg, abstract_mesh(cfg.mesh))
        )
        cases.append(
            _case(builder, cfg, kind="superstep" if tb > 1 else "step")
        )
    assert irc.check_cases(cases) == []
    for case in cases:
        assert irf.check_case(case) == []
        assert ird.check_case(case) == []


def test_broken_permutation_fires_bijection_and_neighbor_graph():
    cfg = _cfg()

    def bad(u):
        # duplicate destination: not a bijection
        g = lax.ppermute(u[:1], "x", [(0, 1), (1, 1)])
        return u + g

    case = _case(_sharded(bad, cfg), cfg)
    codes = _codes(irc.check_cases([case]))
    assert "ANL601" in codes

    def wrong_graph(u):
        # wrap pair on a Dirichlet config: not the mesh neighbor graph
        g1 = lax.ppermute(u[:1], "x", [(0, 1), (1, 0)])
        g2 = lax.ppermute(u[-1:], "x", [(1, 0), (0, 1)])
        return u + g1 + g2

    case2 = _case(_sharded(wrong_graph, cfg), cfg)
    assert "ANL602" in _codes(irc.check_cases([case2]))


def test_missing_inverse_direction_fires_pair_checks():
    cfg = _cfg()
    taps = _solver_taps(cfg)

    def one_way(u):
        # only the low-side ghost travels; the high face never returns
        ghost = lax.ppermute(u[-1:], "x", [(0, 1)])
        up = jnp.concatenate([ghost, u, jnp.zeros_like(u[:1])], 0)
        up = jnp.pad(up, ((0, 0), (1, 1), (1, 1)))
        return apply_taps_padded(up, taps)

    case = _case(_sharded(one_way, cfg), cfg)
    codes = _codes(irc.check_cases([case]))
    assert "ANL605" in codes


def test_divergent_predicate_collective_caught_at_ir_not_ast(tmp_path):
    """THE acceptance hazard: a collective under a shard-varying traced
    predicate deadlocks a pod. The AST tier (ANL101-103) must prove
    blind — lax.cond is data flow, not Python control flow — while the
    IR tier catches it."""
    src = textwrap.dedent(
        """
        import jax
        from jax import lax

        def steppish(u):
            # traced conditional on a shard-varying value: every device
            # runs this PYTHON code identically, so the AST sees nothing
            return lax.cond(
                lax.axis_index("x") == 0,
                lambda v: lax.psum(v, "x"),
                lambda v: v,
                u,
            )
        """
    )
    path = tmp_path / "pkg" / "divergent.py"
    path.parent.mkdir(parents=True)
    path.write_text(src)
    ast_found = ast_collectives.check(str(tmp_path), files=[str(path)])
    assert ast_found == []  # the AST tier is provably blind here

    cfg = _cfg()

    def steppish(u):
        return lax.cond(
            lax.axis_index("x") == 0,
            lambda v: lax.psum(v, "x"),
            lambda v: v,
            u,
        )

    case = _case(
        _sharded(steppish, cfg, out_specs=P("x", None, None)), cfg
    )
    found = [f for f in irc.check_cases([case]) if f.code == "ANL606"]
    assert found, "IR tier must catch the divergent-predicate collective"
    assert "psum" in found[0].message


def test_divergent_while_predicate_caught():
    cfg = _cfg()

    def bad_loop(u):
        # loop bound derived from MY shard's data: trip counts diverge
        # and the psum inside desynchronizes
        n = jnp.max(u).astype(jnp.int32)

        def body(state):
            i, v = state
            return i + 1, v + lax.psum(v, "x")

        _, out = lax.while_loop(lambda s: s[0] < n, body, (0, u))
        return out

    case = _case(_sharded(bad_loop, cfg), cfg)
    assert "ANL606" in _codes(irc.check_cases([case]))


def test_uniform_pmax_bound_is_not_flagged():
    """The EnsembleSolver discipline: a loop bound made uniform by a
    pmax over the varying axis is NOT divergent (the taint is removed),
    so the masked-budget loop certifies clean."""
    cfg = _cfg()

    def good_loop(u):
        n = lax.pmax(jnp.max(u).astype(jnp.int32), "x")

        def body(state):
            i, v = state
            return i + 1, v + lax.psum(v, "x")

        _, out = lax.while_loop(lambda s: s[0] < n, body, (0, u))
        return out

    case = _case(_sharded(good_loop, cfg), cfg)
    assert [f for f in irc.check_cases([case]) if f.code == "ANL606"] == []


def test_unreplicated_unmapped_output_fires_replication_contract():
    cfg = _cfg()

    def local(u):
        # declared replicated (P()) but psum'd over x only... except the
        # value genuinely varies over nothing else here, so use raw sum
        return jnp.sum(u)  # varies over x, never reduced across devices

    case = _case(_sharded(local, cfg, out_specs=P()), cfg)
    assert "ANL607" in _codes(irc.check_cases([case]))


def test_partially_mapped_output_variation_fires_replication():
    """An output sharded over x whose value ALSO varies over sharded y
    (never reduced) is ill-defined stitching — the partial-mapping form
    of the check_vma=False debt."""
    cfg = _cfg(mesh=MeshConfig(shape=(2, 2, 1)))

    def local(u):
        return u * (1.0 + lax.axis_index("y"))

    case = _case(
        shard_map(
            local,
            mesh=abstract_mesh(cfg.mesh),
            in_specs=P("x", None, None),
            out_specs=P("x", None, None),
            check_vma=False,
        ),
        cfg,
        mesh_sizes={"x": 2, "y": 2, "z": 1},
    )
    found = [f for f in irc.check_cases([case]) if f.code == "ANL607"]
    assert found and "'y'" in found[0].message


def test_degraded_device_posture_warns_anl610():
    """A session whose backend initialized below the wanted device
    count must surface ANL610 — the matrix lost its block meshes and
    ensemble programs, and that must never read as a full clean."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    out = subprocess.run(
        [
            sys.executable, "-m", "heat3d_tpu.cli", "lint", "--ir",
            "--checker", "ir-collectives", "--json",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        env=env,
    )
    payload = json.loads(out.stdout)
    assert any(f["code"] == "ANL610" for f in payload["findings"]), (
        out.stdout + out.stderr
    )
    assert out.returncode == 0  # warning severity: visible, not fatal


def test_residual_psum_axes_contract():
    """A residual psum over a PARTIAL axis set fails the replication
    contract; the real residual program passes."""
    cfg = _cfg(mesh=MeshConfig(shape=(2, 2, 1)))
    taps = _solver_taps(cfg)

    def partial_psum(u):
        up = exchange_halo(u, cfg.mesh, cfg.stencil.bc, 0.0, 1)
        new = apply_taps_padded(up, taps)
        r = residual_sumsq(new, u, jnp.dtype("float32"))
        return new, lax.psum(r, ("x",))  # forgot 'y'

    case = _case(
        shard_map(
            partial_psum,
            mesh=abstract_mesh(cfg.mesh),
            in_specs=SPEC,
            out_specs=(SPEC, P()),
            check_vma=False,
        ),
        cfg,
        kind="residual",
        mesh_sizes={"x": 2, "y": 2, "z": 1},
    )
    codes = _codes(irc.check_cases([case]))
    assert "ANL607" in codes

    good = _case(
        make_step_fn(cfg, abstract_mesh(cfg.mesh), with_residual=True),
        cfg,
        kind="residual",
        mesh_sizes={"x": 2, "y": 2, "z": 1},
    )
    assert irc.check_cases([good]) == []


# ---- halo footprint (ANL7xx) ----------------------------------------------


def _starved_superstep_case():
    """Claims time_blocking=2 but exchanges width-1 halos twice — the
    footprint a superstep refactor would produce if it forgot to widen
    the exchange."""
    cfg = dataclasses.replace(_cfg(), time_blocking=2)
    taps = _solver_taps(cfg)

    def starved(u):
        up = exchange_halo(u, cfg.mesh, cfg.stencil.bc, 0.0, width=1)
        mid = apply_taps_padded(up, taps)
        up2 = exchange_halo(mid, cfg.mesh, cfg.stencil.bc, 0.0, width=1)
        return apply_taps_padded(up2, taps)

    return _case(_sharded(starved, cfg), cfg, kind="superstep")


def test_insufficient_ghost_width_fires():
    codes = _codes(irf.check_case(_starved_superstep_case()))
    assert "ANL701" in codes
    assert "ANL703" in codes  # and the trapezoid chain is broken


def test_wasteful_ghost_width_warns():
    cfg = _cfg()  # tb=1: one application needs width 1
    taps = _solver_taps(cfg)

    def wasteful(u):
        up = exchange_halo(u, cfg.mesh, cfg.stencil.bc, 0.0, width=2)
        mid = apply_taps_padded(up, taps)
        return mid[1:-1, 1:-1, 1:-1]

    case = _case(_sharded(wasteful, cfg), cfg)
    found = irf.check_case(case)
    assert any(f.code == "ANL702" and f.severity == "warning" for f in found)


def test_footprint_radius_derivation():
    assert irf.tap_radius(_cfg()) == (1, 1, 1)
    from heat3d_tpu.core.config import StencilConfig

    assert irf.tap_radius(_cfg(stencil=StencilConfig("27pt"))) == (1, 1, 1)


# ---- dtype flow (ANL8xx) --------------------------------------------------


def test_fp64_leak_fires_alien_dtype():
    cfg = _cfg()
    taps = _solver_taps(cfg)

    def leaky(u):
        up = exchange_halo(u, cfg.mesh, cfg.stencil.bc, 0.0, 1)
        with jax.experimental.enable_x64():
            mid = apply_taps_padded(
                up, taps, compute_dtype=jnp.dtype("float64")
            )
        return mid.astype(u.dtype)

    with jax.experimental.enable_x64():
        case = _case(_sharded(leaky, cfg), cfg)
        case.jaxpr()  # trace inside the x64 context
    assert "ANL801" in _codes(ird.check_case(case))


def test_bf16_accumulation_leak_fires():
    cfg = _cfg(precision=Precision.bf16())
    taps = _solver_taps(cfg)

    def lossy(u):
        up = exchange_halo(u, cfg.mesh, cfg.stencil.bc, 0.0, 1)
        new = apply_taps_padded(up, taps, out_dtype=jnp.bfloat16)
        d = (new - u)
        # the local sum upcasts (jax auto-promotes small-float
        # accumulation) but the CROSS-DEVICE reduction runs in bf16 —
        # the forgotten upcast before the psum is the realistic leak
        r = jnp.sum(d * d).astype(jnp.bfloat16)
        return new, lax.psum(r, ("x", "y", "z"))

    case = _case(
        shard_map(
            lossy,
            mesh=abstract_mesh(cfg.mesh),
            in_specs=SPEC,
            out_specs=(SPEC, P()),
            check_vma=False,
        ),
        cfg,
        kind="residual",
    )
    assert "ANL802" in _codes(ird.check_case(case))


def test_missing_roundtrip_fires_and_real_superstep_clean():
    cfg = dataclasses.replace(
        _cfg(precision=Precision.bf16()), time_blocking=2
    )
    taps = _solver_taps(cfg)

    def no_roundtrip(u):
        # computes in f32 but never returns to bf16 between applications
        up = exchange_halo(u, cfg.mesh, cfg.stencil.bc, 0.0, 2)
        mid = apply_taps_padded(
            up, taps, compute_dtype=jnp.float32, out_dtype=jnp.float32
        )
        out = apply_taps_padded(
            mid, taps, compute_dtype=jnp.float32, out_dtype=jnp.float32
        )
        return out.astype(jnp.bfloat16)

    case = _case(_sharded(no_roundtrip, cfg), cfg, kind="superstep")
    assert "ANL803" in _codes(ird.check_case(case))

    from heat3d_tpu.parallel.step import make_superstep_fn

    good = _case(
        make_superstep_fn(cfg, abstract_mesh(cfg.mesh)),
        cfg,
        kind="superstep",
    )
    assert ird.check_case(good) == []


# ---- memory contract (ANL9xx) ---------------------------------------------


def _real_case_1dev(tb=1):
    cfg = dataclasses.replace(
        _cfg(mesh=MeshConfig(shape=(1, 1, 1))), time_blocking=tb
    )
    from heat3d_tpu.parallel.step import make_superstep_fn
    from heat3d_tpu.parallel.topology import build_mesh

    mesh = build_mesh(cfg.mesh)
    builder = (
        make_superstep_fn(cfg, mesh) if tb > 1 else make_step_fn(cfg, mesh)
    )
    case = _case(builder, cfg, kind="superstep" if tb > 1 else "step")
    case.compile = True
    return case


def test_memcontract_clean_on_real_program():
    found = irm.check_cases([_real_case_1dev(tb=2)], compile_enabled=True)
    assert [f for f in found if f.severity == "error"] == []
    assert any(f.code == "ANL904" for f in found)  # joined numbers


def test_memcontract_budget_overrun_fires(monkeypatch):
    case = _real_case_1dev(tb=2)
    monkeypatch.setattr(irm, "temp_model_bytes", lambda cfg: 1)
    found = irm.check_cases([case], compile_enabled=True)
    assert any(f.code == "ANL902" for f in found)


def test_memcontract_signature_drift_fires():
    """A program whose output is not the one-shard ping-pong contract
    (here: a doubled field) breaks the signature check."""
    cfg = _cfg(mesh=MeshConfig(shape=(1, 1, 1)))

    def doubled(u):
        return jnp.stack([u, u])  # two field copies out

    case = _case(doubled, cfg)
    case.compile = True
    found = irm.check_cases([case], compile_enabled=True)
    assert any(f.code == "ANL901" for f in found)


def test_gate_adjudication_fires_on_table_drift():
    found = irm.check_gate_adjudication(
        chip_table={"tpu-tiny": 4 * irm.MIB},
        budget_for=lambda gen: 32 * irm.MIB,
        live_generation="not-in-table",
    )
    assert [f.code for f in found] == ["ANL905"]
    assert found[0].severity == "error"
    # and the real gate resolves within every known generation
    assert irm.check_gate_adjudication() == []


def test_gate_adjudication_fires_on_live_override_above_capacity():
    """An operator HEAT3D_VMEM_BYTES override above the current part's
    VMEM is the mis-set knob the old ANL305 warning existed for — now an
    adjudicated error on the live resolution."""
    found = irm.check_gate_adjudication(
        live_generation="tpu-v5-lite",
        live_budget=64 * irm.MIB,
    )
    assert [f.code for f in found] == ["ANL905"]
    assert "HEAT3D_VMEM_BYTES" in found[0].message
    assert irm.check_gate_adjudication(
        live_generation="tpu-v5-lite", live_budget=16 * irm.MIB
    ) == []


def test_generation_aware_gate_budget(monkeypatch):
    from heat3d_tpu.ops import stencil_dma_fused as dma

    assert dma.chip_vmem_budget_for("tpu-v5-lite") == 16 * 1024 * 1024
    assert dma.chip_vmem_budget_for("tpu-v5p") == 32 * 1024 * 1024
    assert dma.chip_vmem_budget_for("weird-part") == 32 * 1024 * 1024
    monkeypatch.setenv("HEAT3D_VMEM_BYTES", str(7 * 1024 * 1024))
    assert dma._chip_vmem_budget() == 7 * 1024 * 1024
    monkeypatch.delenv("HEAT3D_VMEM_BYTES")
    monkeypatch.setattr(
        "heat3d_tpu.tune.cache.chip_generation", lambda: "tpu-v5-lite"
    )
    assert dma._chip_vmem_budget() == 16 * 1024 * 1024


# ---- fingerprints / framework ---------------------------------------------


def test_ir_fingerprints_anchor_on_config_key_not_trace_text():
    """Two findings for the same (checker, config-key, invariant) with
    different message text (jaxpr pretty-printer drift) share one
    fingerprint; a different config key separates them."""
    from heat3d_tpu.analysis.findings import Finding

    a = Finding(
        checker="ir-collectives", severity="error", path="p.py", line=0,
        code="ANL606", symbol="7pt/fp32/m2x1x1/tb1/axis/step|divergent",
        message="jax 0.4 spelling of the trace",
    )
    b = dataclasses.replace(a, message="jax 0.9 spelling, new pretty printer")
    c = dataclasses.replace(
        a, symbol="27pt/fp32/m2x1x1/tb1/axis/step|divergent"
    )
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != c.fingerprint()


def test_all_ir_findings_carry_config_key_symbols():
    """Checker discipline: every seeded finding has a symbol anchor
    (config-key|invariant), so no fingerprint ever rides on message
    text."""
    found = irc.check_cases([_case(_sharded(
        lambda u: lax.cond(
            lax.axis_index("x") == 0,
            lambda v: lax.psum(v, "x"),
            lambda v: v,
            u,
        ),
        _cfg(), out_specs=P("x", None, None)), _cfg())])
    found += irf.check_case(_starved_superstep_case())
    assert found
    for f in found:
        assert f.symbol and "|" in f.symbol


def test_ir_catalog_and_list():
    assert set(IR_CHECKERS) == {
        "ir-collectives", "ir-footprint", "ir-dtype", "ir-memory"
    }
    from heat3d_tpu.analysis.cli import main as lint_main

    assert lint_main(["--ir", "--list"]) == 0


# ---- acceptance ------------------------------------------------------------


def test_lint_ir_acceptance_clean_on_repo():
    """Acceptance: `heat3d lint --ir --json` certifies the repo's judged
    matrix with zero errors AND zero warnings in a fresh process — run
    exactly as CI runs it (the CLI forces its own multi-device CPU mesh,
    so a degraded single-shard matrix would surface as the ANL610
    warning and fail this test)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-m", "heat3d_tpu.cli", "lint", "--ir", "--json"],
        cwd=REPO,
        capture_output=True,
        text=True,
        env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["counts"]["error"] == 0
    assert payload["counts"]["warning"] == 0
    assert set(payload["checkers"]) == set(IR_CHECKERS)
    # the compiled memory-contract leg genuinely ran
    assert any(f["code"] == "ANL904" for f in payload["findings"])
