"""Persistent halo-exchange plans (heat3d_tpu/parallel/plan.py): plan
cache + audit-event contract, knob threading across the five surfaces,
tuning-cache resolution, bench-row provenance, the partition-aware IR
collective checks, and — the acceptance battery — bitwise plan-vs-ad-hoc
parity plus partitioned-vs-monolithic value identity on a REAL 4-device
CPU mesh subprocess (incl. the serve ensemble traced-bind path)."""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from heat3d_tpu.core.config import (
    BoundaryCondition,
    GridConfig,
    MeshConfig,
    SolverConfig,
    StencilConfig,
)
from heat3d_tpu.parallel import plan as hplan
from heat3d_tpu.parallel.topology import abstract_mesh
from heat3d_tpu.utils.compat import shard_map

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

SPEC = P("x", "y", "z")


def _cfg(**kw):
    kw.setdefault("grid", GridConfig.cube(16))
    kw.setdefault("mesh", MeshConfig(shape=(2, 1, 1)))
    kw.setdefault("backend", "jnp")
    return SolverConfig(**kw)


# ---- the acceptance battery: real 4-device CPU mesh -------------------------


def test_plan_checks_on_cpu_mesh():
    """Bitwise plan-vs-ad-hoc parity (7pt/27pt x tb{1..4} x
    axis/pairwise), partitioned-vs-monolithic identity (incl. the uneven
    decomposition and periodic wrap), and the ensemble traced-bind
    parity — on a genuine 4-device CPU mesh subprocess."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    env["PYTHONPATH"] = os.pathsep.join([REPO, env.get("PYTHONPATH", "")])
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "multidevice_checks.py"), "plan"],
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, (
        f"plan multidevice checks failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}"
    )
    for marker in (
        "plan_bitwise_parity OK",
        "plan_partitioned_identity OK",
        "plan_ensemble_parity OK",
    ):
        assert marker in proc.stdout


# ---- plan cache + audit events ----------------------------------------------


def _read_events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_plan_built_once_per_run_and_reused(tmp_path):
    """The reuse contract: one ``exchange_plan_built`` per plan key per
    run, however many executables trace it (the multistep ping-pong body
    alone calls exchange() three times), with reuse recorded as
    ``plan_cache_hit`` — and a SECOND run in the same process builds
    nothing (the plan cache is persistent, not per-trace)."""
    from heat3d_tpu import obs
    from heat3d_tpu.models.heat3d import HeatSolver3D

    hplan.clear_plan_cache()
    p = str(tmp_path / "plan.ledger.jsonl")
    obs.activate(p, meta={"entry": "test"})
    try:
        cfg = _cfg(mesh=MeshConfig(shape=(1, 1, 1)))
        s = HeatSolver3D(cfg)
        u = s.init_state("hot-cube")
        u = s.run(u, jnp.int32(3))
        # a second executable over the same exchange shape: residual step
        s.step_with_residual(u)
    finally:
        obs.deactivate(rc=0)
    events = _read_events(p)
    built = [e for e in events if e["event"] == "exchange_plan_built"]
    hits = [e for e in events if e["event"] == "plan_cache_hit"]
    assert len(built) == 1, built
    assert built[0]["mode"] == "monolithic"
    assert built[0]["width"] == 1
    assert built[0]["messages_per_exchange"] == 0  # (1,1,1): no remote party
    assert len(hits) == 1  # deduped per (run, key), not per trace call
    # second run, same process: the plan cache serves it — no new build
    p2 = str(tmp_path / "plan2.ledger.jsonl")
    obs.activate(p2, meta={"entry": "test"})
    try:
        s2 = HeatSolver3D(_cfg(mesh=MeshConfig(shape=(1, 1, 1))))
        s2.run(s2.init_state("hot-cube"), jnp.int32(2))
    finally:
        obs.deactivate(rc=0)
    events2 = _read_events(p2)
    assert [e for e in events2 if e["event"] == "exchange_plan_built"] == []
    assert [e for e in events2 if e["event"] == "plan_cache_hit"]


def test_plan_traffic_model():
    """The plan's transport model: messages double under partitioning,
    boundary bytes do not (the A/B trades schedule, not traffic), and
    axis ordering's progressive face extension is priced in."""
    mesh = MeshConfig(shape=(2, 2, 1))
    mono = hplan.build_plan(mesh, BoundaryCondition.DIRICHLET, width=1)
    part = hplan.build_plan(
        mesh, BoundaryCondition.DIRICHLET, width=1, mode="partitioned",
        min_part_bytes=0,
    )
    tm = mono.traffic((8, 8, 16), 4)
    tp = part.traffic((8, 8, 16), 4)
    assert mono.messages_per_exchange() == 4  # 2 sharded axes x 2 faces
    assert part.messages_per_exchange() == 8
    assert tp["bytes_per_device"] == tm["bytes_per_device"]
    assert tp["messages"] == 2 * tm["messages"]
    # axis ordering: the y faces are x-extended (8+2) x 1 x 16
    x_face = 8 * 16 * 4 * 2
    y_face = (8 + 2) * 16 * 4 * 2
    assert tm["bytes_per_device"] == x_face + y_face


def test_partition_granularity_floor():
    """Faces below the granularity floor ship whole (the monolithic
    schedule) even under halo_plan='partitioned' — sub-messages too
    small to pipeline are pure per-collective overhead (the CPU A/B's
    measured regime; docs/TUNING.md)."""
    mesh = MeshConfig(shape=(2, 1, 1))
    gated = hplan.build_plan(
        mesh, BoundaryCondition.DIRICHLET, mode="partitioned",
        min_part_bytes=1 << 20,
    )
    # 16x16 fp32 face = 1 KiB < 1 MiB floor -> monolithic schedule
    assert gated.traffic((16, 16, 16), 4)["messages"] == 2
    # 1024^2 fp32 face = 4 MiB >= floor -> genuine sub-blocks
    assert gated.traffic((1024, 1024, 1024), 4)["messages"] == 4
    forced = hplan.build_plan(
        mesh, BoundaryCondition.DIRICHLET, mode="partitioned",
        min_part_bytes=0,
    )
    assert forced.traffic((16, 16, 16), 4)["messages"] == 4


def test_partition_bounds_tile_exactly():
    for extent, parts in ((16, 2), (7, 2), (3, 4), (1, 2)):
        bounds = hplan.partition_bounds(extent, parts)
        assert bounds[0][0] == 0 and bounds[-1][1] == extent
        assert all(b > a for a, b in bounds)
        assert all(
            bounds[i][1] == bounds[i + 1][0] for i in range(len(bounds) - 1)
        )


# ---- config validation + kernel-route pinning -------------------------------


def test_halo_plan_config_validation():
    with pytest.raises(ValueError, match="halo_plan"):
        _cfg(halo_plan="bogus")
    with pytest.raises(ValueError, match="ppermute"):
        _cfg(halo="dma", halo_plan="partitioned")
    # auto + monolithic + partitioned all construct on ppermute
    for hp in ("monolithic", "partitioned", "auto"):
        assert _cfg(halo_plan=hp).halo_plan == hp


def test_partitioned_pins_the_exchange_path(monkeypatch):
    """halo_plan='partitioned' stands the kernel families down via the
    shared gate (same contract as halo_order='pairwise'): the A/B must
    measure the exchange path, never a kernel that ignores the knob."""
    from heat3d_tpu.parallel.step import _direct_kernel_fn, _kernel_env_gate

    monkeypatch.setenv("HEAT3D_DIRECT_INTERPRET", "1")
    base = _cfg(backend="pallas", mesh=MeshConfig(shape=(1, 1, 1)))
    assert _kernel_env_gate(base)[0] is True
    part = dataclasses.replace(base, halo_plan="partitioned")
    assert _kernel_env_gate(part)[0] is False
    assert _direct_kernel_fn(part, halo=1) is None


# ---- knob surfaces + tuning-cache resolution --------------------------------


def test_halo_plan_on_every_knob_surface():
    from heat3d_tpu.analysis.provenance import ROUTE_FIELDS
    from heat3d_tpu.tune.cache import CONFIG_KNOBS
    from heat3d_tpu.tune.space import (
        DEFAULT_KNOBS,
        check_concrete,
        parse_knob_values,
    )

    assert "halo_plan" in CONFIG_KNOBS
    assert DEFAULT_KNOBS["halo_plan"] == ("monolithic", "partitioned")
    assert "halo_plan" in ROUTE_FIELDS
    assert parse_knob_values("halo_plan", "monolithic,partitioned") == (
        "monolithic",
        "partitioned",
    )
    with pytest.raises(ValueError, match="concrete"):
        parse_knob_values("halo_plan", "auto")
    with pytest.raises(ValueError, match="concrete"):
        check_concrete({"halo_plan": ("auto",)})


def test_halo_plan_resolves_through_tune_cache(tmp_path):
    """halo_plan='auto' resolves to the cached winner; an entry
    predating the knob (schema drift) degrades to the static fallback
    (monolithic) instead of crashing resolution."""
    from heat3d_tpu.tune import cache as tcache

    store = str(tmp_path / "tune_cache.json")
    base = _cfg(mesh=MeshConfig(shape=(1, 1, 1)))
    winner = dataclasses.replace(base, halo_plan="partitioned")
    key = tcache.cache_key(base)
    tcache.store_entry(key, winner, 1.0, path=store)
    resolved = tcache.resolve_config(
        dataclasses.replace(base, halo_plan="auto"), path=store
    )
    assert resolved.halo_plan == "partitioned"
    # explicit knobs are never overridden
    explicit = tcache.resolve_config(base, path=store)
    assert explicit.halo_plan == "monolithic"
    # legacy entry missing the knob -> stale -> static fallback
    doc = json.load(open(store))
    del doc["entries"][key]["config"]["halo_plan"]
    json.dump(doc, open(store, "w"))
    legacy = tcache.resolve_config(
        dataclasses.replace(base, halo_plan="auto"), path=store
    )
    assert legacy.halo_plan == "monolithic"


def test_tune_apply_and_show_annotate_partitioned(tmp_path, capsys):
    from heat3d_tpu.tune import cache as tcache
    from heat3d_tpu.tune.cli import main as tune_main

    store = str(tmp_path / "tune_cache.json")
    base = _cfg(mesh=MeshConfig(shape=(1, 1, 1)))
    winner = dataclasses.replace(base, halo_plan="partitioned")
    key = tcache.cache_key(base)
    tcache.store_entry(key, winner, 2.0, default_metric=1.5, path=store)
    assert tune_main(["apply", "--key", key, "--cache", store]) == 0
    out = capsys.readouterr().out
    assert "--halo-plan partitioned" in out
    assert tune_main(["show", "--cache", store]) == 0
    out = capsys.readouterr().out
    assert "partitioned-exchange winner" in out


# ---- bench-row provenance ---------------------------------------------------


def test_bench_rows_carry_halo_plan(tmp_path):
    from heat3d_tpu.bench.harness import bench_halo, bench_throughput

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_provenance as cp
    finally:
        sys.path.pop(0)
    cfg = _cfg(grid=GridConfig.cube(8), mesh=MeshConfig(shape=(1, 1, 1)))
    row = bench_throughput(cfg, steps=2, warmup=1, repeats=1)
    assert row["halo_plan"] == "monolithic"
    assert cp.check_row(row) == []
    halo = bench_halo(
        dataclasses.replace(cfg, halo_plan="partitioned"),
        iters=2, warmup=1, k=2,
    )
    assert halo["halo_plan"] == "partitioned"
    # the plan's own transport model rides the row (planned-exchange arm)
    assert halo["plan_messages_per_exchange"] == 0  # (1,1,1): no ICI
    assert halo["plan_bytes_per_device"] == 0
    assert cp.check_row(halo) == []
    legacy = dict(halo)
    legacy.pop("halo_plan")
    assert any("halo_plan" in p for p in cp.check_row(legacy))


def test_no_plan_escape_records_effective_mode(monkeypatch):
    """Under HEAT3D_NO_PLAN=1 a requested-partitioned config executes
    the ad-hoc monolithic schedule — rows and sweep-journal keys must
    record THAT, or the escape hatch corrupts the plan A/B (review
    finding)."""
    from heat3d_tpu.bench.harness import bench_halo
    from heat3d_tpu.parallel.plan import effective_halo_plan
    from heat3d_tpu.resilience.sweepstate import row_key

    cfg = _cfg(
        grid=GridConfig.cube(8), mesh=MeshConfig(shape=(1, 1, 1)),
        halo_plan="partitioned",
    )
    assert effective_halo_plan(cfg) == "partitioned"
    assert ":hppartitioned" in row_key(cfg, "halo")
    monkeypatch.setenv("HEAT3D_NO_PLAN", "1")
    assert effective_halo_plan(cfg) == "monolithic"
    assert ":hppartitioned" not in row_key(cfg, "halo")
    row = bench_halo(cfg, iters=2, warmup=1, k=2)
    assert row["halo_plan"] == "monolithic"


def test_roofline_path_labels_partitioned_rows():
    from heat3d_tpu.obs.perf.roofline import bytes_per_cell_update

    row = {
        "dtype": "float32", "time_blocking": 1, "mesh": [2, 1, 1],
        "halo": "ppermute", "direct_path": False,
        "halo_plan": "partitioned",
    }
    per_update, path = bytes_per_cell_update(row)
    assert "planned-partitioned" in path
    row_mono = dict(row, halo_plan="monolithic")
    per_mono, path_mono = bytes_per_cell_update(row_mono)
    assert per_update == per_mono  # same bytes — the A/B trades schedule
    assert "planned" not in path_mono


# ---- partition-aware IR collective checks -----------------------------------


def _ir_case(fn, cfg, key="seed-plan"):
    from heat3d_tpu.analysis.ir import programs as irp

    aval = jax.ShapeDtypeStruct(
        cfg.padded_shape, jnp.dtype(cfg.precision.storage)
    )
    return irp.ProgramCase(
        key=key,
        cfg=cfg,
        kind="step",
        path="tests/seeded.py",
        fn=fn,
        avals=(aval,),
        mesh_sizes=dict(zip(cfg.mesh.axis_names, cfg.mesh.shape)),
    )


def _sharded(fn, cfg, out_specs=SPEC):
    return shard_map(
        fn,
        mesh=abstract_mesh(cfg.mesh),
        in_specs=SPEC,
        out_specs=out_specs,
        check_vma=False,
    )


def test_ir_accepts_partitioned_step_program(monkeypatch):
    """A REAL plan-built partitioned step program (granularity floor
    zeroed, so 16^3 faces genuinely split) certifies clean through the
    collective-topology family (sub-block permutes compose to the
    inverse-pair ring shifts, face sub-blocks tile the contracted
    extents) — and it really traces MORE than the 2-per-axis monolithic
    permute count."""
    from heat3d_tpu.analysis.ir import collectives as irc, jaxpr_tools as jt
    from heat3d_tpu.parallel.step import make_step_fn

    monkeypatch.setenv(hplan.ENV_PART_MIN_BYTES, "0")
    hplan.clear_plan_cache()
    cfg = _cfg(halo_plan="partitioned", mesh=MeshConfig(shape=(2, 2, 1)))
    case = _ir_case(
        make_step_fn(cfg, abstract_mesh(cfg.mesh)), cfg,
        key="plan-partitioned-clean",
    )
    pp = [
        s
        for s in jt.collect_collectives(case.jaxpr())
        if s.prim == "ppermute"
    ]
    assert len(pp) == 8  # 2 sharded axes x 2 faces x 2 sub-blocks
    findings = [
        f
        for f in irc.check_cases([case])
        if f.code in ("ANL601", "ANL602", "ANL603", "ANL604", "ANL605")
    ]
    assert findings == [], [f.message for f in findings]


def test_ir_accepts_partitioned_periodic_size2_ring(monkeypatch):
    """On a periodic size-2 ring shift(+1) == shift(-1) (self-inverse),
    so BOTH face directions' sub-blocks land in one permutation class —
    the tile-sum rule must accept them covering the extent exactly twice
    (review finding: this fired a false ANL604 on a provably
    bitwise-correct program)."""
    from heat3d_tpu.analysis.ir import collectives as irc
    from heat3d_tpu.parallel.step import make_step_fn

    monkeypatch.setenv(hplan.ENV_PART_MIN_BYTES, "0")
    hplan.clear_plan_cache()
    cfg = _cfg(
        halo_plan="partitioned",
        stencil=StencilConfig(bc=BoundaryCondition.PERIODIC),
    )
    case = _ir_case(
        make_step_fn(cfg, abstract_mesh(cfg.mesh)), cfg,
        key="plan-partitioned-periodic2",
    )
    findings = [
        f
        for f in irc.check_cases([case])
        if f.code in ("ANL601", "ANL602", "ANL603", "ANL604", "ANL605")
    ]
    assert findings == [], [f.message for f in findings]


def test_ir_flags_unbalanced_partitioned_directions():
    """A sub-block shipped one way and never returned is an unmatched
    transfer: ANL605 direction-balance fires (the partitioned analogue
    of a missing face)."""
    from heat3d_tpu.analysis.ir import collectives as irc
    from heat3d_tpu.parallel.halo import shift_perm

    cfg = _cfg(halo_plan="partitioned")
    up = shift_perm(2, +1, False)
    down = shift_perm(2, -1, False)

    def bad(u):
        hi = u[-1:]
        lo = u[:1]
        # two sub-blocks up, only ONE down: unbalanced directions
        g1 = lax.ppermute(hi[:, :8], "x", up)
        g2 = lax.ppermute(hi[:, 8:], "x", up)
        g3 = lax.ppermute(lo, "x", down)
        return u + g1.sum() + g2.sum() + g3.sum()

    findings = irc.check_cases([_ir_case(_sharded(bad, cfg), cfg)])
    msgs = [f.message for f in findings if f.code == "ANL605"]
    assert any("balanced" in m for m in msgs), [f.message for f in findings]


def test_ir_flags_partitions_that_do_not_tile_the_face():
    """Partitioned sub-blocks must tile the contracted face extent
    exactly — two 6-wide strips of a 16-wide face (a gap) fire ANL604."""
    from heat3d_tpu.analysis.ir import collectives as irc
    from heat3d_tpu.parallel.halo import shift_perm

    cfg = _cfg(halo_plan="partitioned")
    up = shift_perm(2, +1, False)
    down = shift_perm(2, -1, False)

    def gappy(u):
        hi = u[-1:]
        lo = u[:1]
        acc = u * 1.0
        for a, b in ((0, 6), (6, 12)):  # 12 of 16 covered — gap
            acc = acc + lax.ppermute(hi[:, a:b], "x", up).sum()
            acc = acc + lax.ppermute(lo[:, a:b], "x", down).sum()
        return acc

    findings = irc.check_cases([_ir_case(_sharded(gappy, cfg), cfg)])
    assert "ANL604" in {f.code for f in findings}, [
        f.message for f in findings
    ]


def test_ir_monolithic_still_rejects_multiplicity():
    """The partitioned allowance is gated on the plan mode: the same
    sub-block multiplicity on a MONOLITHIC program stays an ANL605."""
    from heat3d_tpu.analysis.ir import collectives as irc
    from heat3d_tpu.parallel.halo import shift_perm

    cfg = _cfg()  # halo_plan='monolithic'
    up = shift_perm(2, +1, False)
    down = shift_perm(2, -1, False)

    def split(u):
        hi = u[-1:]
        lo = u[:1]
        acc = u * 1.0
        for a, b in ((0, 8), (8, 16)):
            acc = acc + lax.ppermute(hi[:, a:b], "x", up).sum()
            acc = acc + lax.ppermute(lo[:, a:b], "x", down).sum()
        return acc

    findings = irc.check_cases([_ir_case(_sharded(split, cfg), cfg)])
    msgs = [f.message for f in findings if f.code == "ANL605"]
    assert any("MONOLITHIC" in m for m in msgs), [
        f.message for f in findings
    ]
