"""Distributed-path tests.

Executable tier: the full shard_map machinery on a (1,1,1) mesh must equal
the single-device step bitwise (the '-np 1 vs -np P' check, SURVEY.md §4).
Compile-only tier: multi-chip meshes lower via AbstractMesh with the
expected collectives present — the single-chip substitute for a pod
(SURVEY.md §7.0: no multi-device simulation exists on this box).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import abstract_lowering_supported

from heat3d_tpu.core.config import (
    BoundaryCondition,
    GridConfig,
    MeshConfig,
    Precision,
    SolverConfig,
    StencilConfig,
)
from heat3d_tpu.core import golden
from heat3d_tpu.core.stencils import STENCILS, stencil_taps
from heat3d_tpu.ops.stencil_jnp import step_single_device
from heat3d_tpu.parallel.halo import exchange_halo
from heat3d_tpu.parallel.step import (
    make_converge_fn,
    make_multistep_fn,
    make_step_fn,
    make_superstep_fn,
)
from heat3d_tpu.parallel.topology import abstract_mesh, build_mesh, lower_for_mesh
from heat3d_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P


def solo_cfg(n=8, kind="7pt", bc=BoundaryCondition.DIRICHLET, bc_value=0.0,
             precision=Precision.fp32()):
    return SolverConfig(
        grid=GridConfig.cube(n),
        stencil=StencilConfig(kind=kind, bc=bc, bc_value=bc_value),
        mesh=MeshConfig(shape=(1, 1, 1)),
        precision=precision,
        backend="jnp",
    )


# ---- executable on this box ------------------------------------------------


@pytest.mark.parametrize("kind", ["7pt", "27pt"])
@pytest.mark.parametrize(
    "bc,bc_value",
    [
        (BoundaryCondition.DIRICHLET, 0.0),
        (BoundaryCondition.DIRICHLET, 2.0),
        (BoundaryCondition.PERIODIC, 0.0),
    ],
)
def test_sharded_equals_single_device(kind, bc, bc_value):
    cfg = solo_cfg(kind=kind, bc=bc, bc_value=bc_value)
    mesh = build_mesh(cfg.mesh)
    step = make_step_fn(cfg, mesh)
    u = jnp.asarray(golden.random_init((8, 8, 8), seed=4))
    got = jax.jit(step)(u)
    taps = stencil_taps(STENCILS[kind], 1.0, cfg.grid.effective_dt(), (1.0,) * 3)
    want = step_single_device(u, taps, bc, bc_value)
    # Same math and precision; XLA may fuse the two programs differently
    # (observed: 1-ulp fma differences), so compare at ulp scale, not bitwise.
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_halo_111_mesh_equals_pad():
    # On a (1,1,1) mesh the halo exchange must reproduce pad_local exactly:
    # periodic wrap = self-exchange, Dirichlet = bc fill.
    from heat3d_tpu.ops.stencil_jnp import pad_local

    u = jnp.asarray(golden.random_init((5, 6, 7), seed=9))
    for bc, bcv in [
        (BoundaryCondition.PERIODIC, 0.0),
        (BoundaryCondition.DIRICHLET, 0.0),
        (BoundaryCondition.DIRICHLET, 3.5),
    ]:
        cfg = MeshConfig(shape=(1, 1, 1))
        mesh = build_mesh(cfg)
        f = shard_map(
            lambda x: exchange_halo(x, cfg, bc, bcv),
            mesh=mesh,
            in_specs=P("x", "y", "z"),
            out_specs=P("x", "y", "z"),
        )
        got = f(u)
        want = pad_local(u, bc, bcv)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("kind", ["7pt", "27pt"])
@pytest.mark.parametrize(
    "bc,bc_value",
    [
        (BoundaryCondition.DIRICHLET, 0.0),
        (BoundaryCondition.DIRICHLET, 2.0),
        (BoundaryCondition.PERIODIC, 0.0),
    ],
)
def test_overlap_step_equals_unsplit(kind, bc, bc_value):
    """The interior/boundary-split overlap step computes cell-for-cell the
    same expression as the unsplit step — results must agree to ulp."""
    import dataclasses

    cfg = solo_cfg(kind=kind, bc=bc, bc_value=bc_value)
    cfg_ov = dataclasses.replace(cfg, overlap=True)
    mesh = build_mesh(cfg.mesh)
    u = jnp.asarray(golden.random_init((8, 8, 8), seed=21))
    got = jax.jit(make_step_fn(cfg_ov, mesh))(u)
    want = jax.jit(make_step_fn(cfg, mesh))(u)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_overlap_rejects_tiny_local_blocks():
    import dataclasses

    cfg = dataclasses.replace(solo_cfg(n=2), overlap=True)
    with pytest.raises(ValueError, match="overlap"):
        make_step_fn(cfg, build_mesh(cfg.mesh))


@pytest.mark.skipif(
    not abstract_lowering_supported(),
    reason="this jax cannot jit-lower over AbstractMesh (0.4.x gap)",
)
def test_overlap_multichip_lowers_with_collectives():
    cfg = SolverConfig(
        grid=GridConfig.cube(16),
        mesh=MeshConfig(shape=(2, 2, 2)),
        backend="jnp",
        overlap=True,
    )
    am = abstract_mesh(cfg.mesh)
    step = make_step_fn(cfg, am, with_residual=True)
    lowered = lower_for_mesh(
        step, cfg.mesh, (cfg.grid.shape, jnp.float32, P("x", "y", "z"))
    )
    txt = lowered.as_text()
    assert "collective-permute" in txt or "collective_permute" in txt


@pytest.mark.parametrize("kind", ["7pt", "27pt"])
@pytest.mark.parametrize(
    "bc,bc_value",
    [
        (BoundaryCondition.DIRICHLET, 0.0),
        (BoundaryCondition.DIRICHLET, 2.0),
        (BoundaryCondition.PERIODIC, 0.0),
    ],
)
@pytest.mark.parametrize("steps", [1, 2, 5])
@pytest.mark.parametrize("k", [2, 3, 5])
def test_time_blocking_equals_single_steps(kind, bc, bc_value, steps, k):
    """The temporally-blocked loop (k updates per width-k exchange) must
    reproduce the plain per-step loop for any remainder."""
    import dataclasses

    cfg = solo_cfg(kind=kind, bc=bc, bc_value=bc_value)
    cfgk = dataclasses.replace(cfg, time_blocking=k)
    mesh = build_mesh(cfg.mesh)
    u = jnp.asarray(golden.random_init((8, 8, 8), seed=33))
    got = jax.jit(make_multistep_fn(cfgk, mesh))(u, jnp.int32(steps))
    want = jax.jit(make_multistep_fn(cfg, mesh))(u, jnp.int32(steps))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_time_blocking_rejects_overlap():
    import dataclasses

    base = dataclasses.replace(solo_cfg(), time_blocking=2)
    mesh = build_mesh(base.mesh)
    # halo='dma' composes with time blocking (width-k slab exchange); only
    # the overlap split remains mutually exclusive with the superstep
    with pytest.raises(ValueError, match="mutually exclusive"):
        make_superstep_fn(dataclasses.replace(base, overlap=True), mesh)


@pytest.mark.parametrize(
    "n,k,ok",
    [
        (2, 2, False),  # below the 3-cell interior floor
        (3, 2, True),
        (3, 3, True),
        (3, 4, False),  # k ghost layers don't fit
        (4, 4, True),
        (4, 5, False),
    ],
)
def test_deep_tb_local_extent_validation(n, k, ok):
    """The superstep needs local extents >= max(3, k): k ghost layers
    plus a genuine interior for the shrinking recompute rings."""
    import dataclasses

    cfg = dataclasses.replace(solo_cfg(n=n), time_blocking=k)
    mesh = build_mesh(cfg.mesh)
    if ok:
        make_superstep_fn(cfg, mesh)  # builds without raising
    else:
        with pytest.raises(ValueError, match="needs local extents"):
            make_superstep_fn(cfg, mesh)


def test_pairwise_rejects_deep_tb():
    """halo_order='pairwise' stays excluded for every tb > 1 — the deep
    supersteps' shrinking rings read edge/corner ghosts only axis-ordered
    exchange fills (config validation, shared with the tuner's pruning)."""
    for k in (2, 3, 4):
        with pytest.raises(ValueError, match="pairwise"):
            SolverConfig(
                grid=GridConfig.cube(8),
                mesh=MeshConfig(shape=(1, 1, 1)),
                halo_order="pairwise",
                time_blocking=k,
            )


def test_superstep_cell_updates_and_redundant_frac():
    """The trapezoid cost model: raw counts the shrinking-ring recompute,
    effective the k useful sweeps, and the frac is their honest gap."""
    import dataclasses

    from heat3d_tpu.parallel.step import (
        redundant_flops_frac,
        superstep_cell_updates,
    )

    cfg1 = solo_cfg(n=8)
    raw, eff = superstep_cell_updates(cfg1)
    assert raw == eff == 512 and redundant_flops_frac(cfg1) == 0.0
    cfg3 = dataclasses.replace(cfg1, time_blocking=3)
    raw, eff = superstep_cell_updates(cfg3)
    # applications over 12^3, 10^3, 8^3 vs 3 useful 8^3 sweeps
    assert raw == 12**3 + 10**3 + 8**3
    assert eff == 3 * 8**3
    assert redundant_flops_frac(cfg3) == pytest.approx(1 - eff / raw)
    # deeper k, larger frac; bigger blocks, smaller frac
    cfg4 = dataclasses.replace(cfg1, time_blocking=4)
    assert redundant_flops_frac(cfg4) > redundant_flops_frac(cfg3)
    big = dataclasses.replace(solo_cfg(n=64), time_blocking=4)
    assert redundant_flops_frac(big) < redundant_flops_frac(cfg4)


def test_residual_psum_replicated():
    cfg = solo_cfg()
    mesh = build_mesh(cfg.mesh)
    step = make_step_fn(cfg, mesh, with_residual=True)
    u = jnp.asarray(golden.gaussian_init((8, 8, 8)))
    u2, r2 = jax.jit(step)(u)
    want = float(jnp.sum((u2.astype(jnp.float32) - u) ** 2))
    assert float(r2) == pytest.approx(want, rel=1e-6)


def test_convergence_residual_decreases():
    cfg = solo_cfg()
    mesh = build_mesh(cfg.mesh)
    conv = jax.jit(make_converge_fn(cfg, mesh))
    u = jnp.asarray(golden.gaussian_init((8, 8, 8)))
    u1, s1, r1 = conv(u, jnp.int32(3), jnp.float32(0.0))
    u2, s2, r2 = conv(u1, jnp.int32(3), jnp.float32(0.0))
    assert int(s1) == 3 and int(s2) == 3
    assert float(r2) < float(r1)
    # generous tol converges immediately-ish
    _, s3, _ = conv(u2, jnp.int32(50), jnp.float32(1e3))
    assert int(s3) <= 1


def test_multistep_traced_step_count():
    cfg = solo_cfg()
    mesh = build_mesh(cfg.mesh)
    ms = jax.jit(make_multistep_fn(cfg, mesh))
    step = jax.jit(make_step_fn(cfg, mesh))
    u = jnp.asarray(golden.random_init((8, 8, 8), seed=11))
    got = ms(u, jnp.int32(3))
    want = step(step(step(u)))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
    )


# ---- compile-only: multi-chip meshes (SURVEY.md §4 distributed tier) -------


@pytest.mark.parametrize(
    "mesh_shape,kind",
    [
        ((8, 1, 1), "7pt"),   # config 2: 1D slab v5p-8
        ((2, 2, 2), "7pt"),   # config 3: 3D block v5p-8
        ((4, 4, 4), "27pt"),  # config 4: v5p-64
    ],
)
@pytest.mark.skipif(
    not abstract_lowering_supported(),
    reason="this jax cannot jit-lower over AbstractMesh (0.4.x gap)",
)
def test_multichip_step_lowers_with_collectives(mesh_shape, kind):
    n = 16 if max(mesh_shape) <= 4 else 32
    cfg = SolverConfig(
        grid=GridConfig.cube(max(n, max(mesh_shape) * 2)),
        stencil=StencilConfig(kind=kind),
        mesh=MeshConfig(shape=mesh_shape),
        backend="jnp",
    )
    am = abstract_mesh(cfg.mesh)
    step = make_step_fn(cfg, am, with_residual=True)
    lowered = lower_for_mesh(
        step, cfg.mesh,
        (cfg.grid.shape, jnp.float32, P("x", "y", "z")),
    )
    txt = lowered.as_text()
    assert "collective-permute" in txt or "collective_permute" in txt
    assert "all-reduce" in txt or "all_reduce" in txt  # the residual psum


@pytest.mark.skipif(
    not abstract_lowering_supported(),
    reason="this jax cannot jit-lower over AbstractMesh (0.4.x gap)",
)
def test_bf16_strong_scale_config_lowers():
    # config 5: bf16 stencil + fp32 residual on a 128-chip mesh
    cfg = SolverConfig(
        grid=GridConfig.cube(256),
        mesh=MeshConfig(shape=(8, 4, 4)),
        precision=Precision.bf16(),
        backend="jnp",
    )
    am = abstract_mesh(cfg.mesh)
    step = make_step_fn(cfg, am, with_residual=True)
    lowered = lower_for_mesh(
        step, cfg.mesh, (cfg.grid.shape, jnp.bfloat16, P("x", "y", "z"))
    )
    txt = lowered.as_text()
    assert "bf16" in txt
    assert "f32" in txt  # fp32 residual accumulation survives


@pytest.mark.parametrize("kind", ["7pt", "27pt"])
@pytest.mark.skipif(
    not abstract_lowering_supported(),
    reason="this jax cannot jit-lower over AbstractMesh (0.4.x gap)",
)
def test_dma_halo_step_lowers_for_multichip_tpu(kind):
    """The Pallas RDMA halo path (halo='dma') composes with the full step
    and lowers to Mosaic (tpu_custom_call) for a (2,2,2) mesh — the
    compile-only tier for the CUDA-aware-analogue transport."""
    cfg = SolverConfig(
        grid=GridConfig.cube(16),
        stencil=StencilConfig(kind=kind),
        mesh=MeshConfig(shape=(2, 2, 2)),
        backend="jnp",
        halo="dma",
    )
    am = abstract_mesh(cfg.mesh)
    step = make_step_fn(cfg, am, with_residual=True)
    lowered = lower_for_mesh(
        step, cfg.mesh, (cfg.grid.shape, jnp.float32, P("x", "y", "z"))
    )
    txt = lowered.as_text()
    assert "tpu_custom_call" in txt  # the Mosaic DMA kernels
    assert "all-reduce" in txt or "all_reduce" in txt  # residual psum


@pytest.mark.parametrize("width", [2, 3])
@pytest.mark.skipif(
    not abstract_lowering_supported(),
    reason="this jax cannot jit-lower over AbstractMesh (0.4.x gap)",
)
def test_dma_halo_superstep_lowers_for_multichip_tpu(width):
    """Temporal blocking over the RDMA transport: the width-k slab exchange
    (ops/halo_pallas.py) composes with the k-update superstep and lowers to
    Mosaic for a (2,2,2) mesh. Execution parity for the width-k DMA kernels
    is covered per-axis on the 8-device CPU ring (multidevice_checks) since
    interpret mode cannot discharge multi-axis remote DMA (jax 0.9)."""
    cfg = SolverConfig(
        grid=GridConfig.cube(16),
        stencil=StencilConfig(kind="27pt"),
        mesh=MeshConfig(shape=(2, 2, 2)),
        backend="jnp",
        halo="dma",
        time_blocking=width,
    )
    am = abstract_mesh(cfg.mesh)
    step = make_superstep_fn(cfg, am)
    lowered = lower_for_mesh(
        step, cfg.mesh, (cfg.grid.shape, jnp.float32, P("x", "y", "z"))
    )
    txt = lowered.as_text()
    assert "tpu_custom_call" in txt  # the Mosaic DMA kernels


@pytest.mark.parametrize("kind", ["7pt", "27pt"])
@pytest.mark.skipif(
    not abstract_lowering_supported(),
    reason="this jax cannot jit-lower over AbstractMesh (0.4.x gap)",
)
def test_faces_direct_step_lowers_for_multichip_tpu(kind, monkeypatch):
    """The multi-chip faces-direct step and tb=2 superstep — Mosaic direct
    kernels + faces-only ppermute exchange + shell patches — lower for a
    (2,2,2) TPU mesh (HEAT3D_DIRECT_FORCE selects the real kernels
    off-hardware; pallas->Mosaic lowering runs host-side, so block-spec
    violations surface here, not on the chip)."""
    monkeypatch.setenv("HEAT3D_DIRECT_FORCE", "1")
    from heat3d_tpu.parallel.step import _direct_kernel_fn

    for bc in (BoundaryCondition.DIRICHLET, BoundaryCondition.PERIODIC):
        cfg = SolverConfig(
            grid=GridConfig.cube(32),
            stencil=StencilConfig(kind=kind, bc=bc, bc_value=0.5),
            mesh=MeshConfig(shape=(2, 2, 2)),
            backend="auto",
        )
        assert _direct_kernel_fn(cfg, 1, multichip=True) is not None
        am = abstract_mesh(cfg.mesh)
        step = make_step_fn(cfg, am, with_residual=True)
        txt = lower_for_mesh(
            step, cfg.mesh, (cfg.grid.shape, jnp.float32, P("x", "y", "z"))
        ).as_text()
        assert "tpu_custom_call" in txt  # Mosaic direct kernel
        assert "collective_permute" in txt  # faces exchange
        cfg2 = SolverConfig(
            grid=GridConfig.cube(32), stencil=cfg.stencil, mesh=cfg.mesh,
            backend="auto", time_blocking=2,
        )
        sstep = make_superstep_fn(cfg2, am)
        txt2 = lower_for_mesh(
            sstep, cfg2.mesh, (cfg2.grid.shape, jnp.float32, P("x", "y", "z"))
        ).as_text()
        assert "tpu_custom_call" in txt2 and "collective_permute" in txt2


@pytest.mark.skipif(
    not abstract_lowering_supported(),
    reason="this jax cannot jit-lower over AbstractMesh (0.4.x gap)",
)
def test_faces_direct_step_materializes_no_padded_volume(monkeypatch):
    """The architectural claim, checked mechanically in the lowered HLO:
    the exchange path concatenates a full (n+2)^3 padded copy of every
    shard per step; the faces-direct path's largest concatenate is a
    3-thick boundary slab. (32^3 over (2,2,2): local 16^3, padded 18^3.)"""
    import re

    def concat_shapes(cfg):
        am = abstract_mesh(cfg.mesh)
        txt = lower_for_mesh(
            make_step_fn(cfg, am), cfg.mesh,
            (cfg.grid.shape, jnp.float32, P("x", "y", "z")),
        ).as_text()
        return {
            tuple(map(int, m))
            for m in re.findall(
                r"stablehlo\.concatenate.*?->\s*tensor<(\d+)x(\d+)x(\d+)xf32>",
                txt,
            )
        }

    monkeypatch.setenv("HEAT3D_DIRECT_FORCE", "1")
    cfg = SolverConfig(
        grid=GridConfig.cube(32), stencil=StencilConfig(kind="7pt"),
        mesh=MeshConfig(shape=(2, 2, 2)), backend="auto",
    )
    direct_shapes = concat_shapes(cfg)
    assert all(min(s) <= 3 for s in direct_shapes), direct_shapes

    import dataclasses

    monkeypatch.setenv("HEAT3D_NO_DIRECT", "1")
    exchange_shapes = concat_shapes(
        dataclasses.replace(cfg, backend="jnp")
    )
    assert (18, 18, 18) in exchange_shapes, exchange_shapes


def test_unknown_halo_transport_rejected():
    with pytest.raises(ValueError, match="halo transport"):
        SolverConfig(grid=GridConfig.cube(8), halo="nccl")


@pytest.mark.skipif(
    not abstract_lowering_supported(),
    reason="this jax cannot jit-lower over AbstractMesh (0.4.x gap)",
)
def test_multistep_loop_is_device_side():
    cfg = SolverConfig(
        grid=GridConfig.cube(16),
        mesh=MeshConfig(shape=(2, 2, 2)),
        backend="jnp",
    )
    am = abstract_mesh(cfg.mesh)
    ms = make_multistep_fn(cfg, am)
    lowered = lower_for_mesh(
        ms, cfg.mesh,
        (cfg.grid.shape, jnp.float32, P("x", "y", "z")),
        jax.ShapeDtypeStruct((), jnp.int32),
    )
    assert "while" in lowered.as_text()


@pytest.mark.skipif(
    jax.devices()[0].platform != "tpu", reason="needs the TPU compiler"
)
def test_multistep_pair_loop_compiles_copy_free():
    """Regression for the round-2 profile finding: a single-buffer while
    carry makes XLA clone the full volume every iteration (the stencil
    custom-call cannot write into the buffer it reads; measured 38-49% of
    step time). The ping-pong pair carry (_pingpong_loop) must compile to
    a main loop body of exactly two stencil custom-calls and ZERO
    full-volume copies; only the bounded trailing-remainder loops may
    keep one."""
    import re

    n = 128
    cfg = SolverConfig(grid=GridConfig.cube(n), mesh=MeshConfig(shape=(1, 1, 1)))
    mesh = build_mesh(cfg.mesh)
    run = make_multistep_fn(cfg, mesh)
    u = jnp.ones((n, n, n), jnp.float32)
    txt = (
        jax.jit(run, donate_argnums=0)
        .lower(u, jnp.int32(20))
        .compile()
        .as_text()
    )
    cur = None
    copies: dict = {}
    calls: dict = {}
    for ln in txt.splitlines():
        if ln.rstrip().endswith("{"):
            cur = ln.split()[0]
        if re.search(r"= f32\[%d,%d,%d\]\S* copy\(" % (n, n, n), ln):
            copies[cur] = copies.get(cur, 0) + 1
        if "custom-call" in ln:
            calls[cur] = calls.get(cur, 0) + 1
    pair_bodies = [c for c, k in calls.items() if k == 2]
    assert pair_bodies, f"no two-call pair-loop body found in: {calls}"
    for c in pair_bodies:
        assert copies.get(c, 0) == 0, (
            f"full-volume copy reappeared in pair-loop body {c}: {copies}"
        )
