"""Launch the real 8-device distributed checks in a CPU-mesh subprocess.

The dev box's axon PJRT plugin (single real TPU) is injected by
sitecustomize only when PALLAS_AXON_POOL_IPS is set; unsetting it frees
JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count=8 to provide a
genuine 8-device mesh. This is the moral equivalent of the reference
class's ``mpirun -np 8`` single-node oversubscription test (SURVEY.md §4).
"""

import os
import pytest
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.mark.slow
def test_multidevice_checks_on_cpu_mesh():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disable axon plugin injection
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(HERE), env.get("PYTHONPATH", "")]
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "multidevice_checks.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"multidevice checks failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "ALL MULTIDEVICE CHECKS PASSED" in proc.stdout
