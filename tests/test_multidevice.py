"""Launch the real 8-device distributed checks in a CPU-mesh subprocess.

The dev box's axon PJRT plugin (single real TPU) is injected by
sitecustomize only when PALLAS_AXON_POOL_IPS is set; unsetting it frees
JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count=8 to provide a
genuine 8-device mesh. This is the moral equivalent of the reference
class's ``mpirun -np 8`` single-node oversubscription test (SURVEY.md §4).
"""

import os
import pytest
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def _cpu_mesh_env(ndev: int) -> dict:
    """Env for a genuine ndev-device virtual CPU mesh subprocess: neutralize
    the axon plugin injection, force the CPU platform, size the host
    device count, and put the repo root on PYTHONPATH."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # disable axon plugin injection
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={ndev}"
    ).strip()
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(HERE), env.get("PYTHONPATH", "")]
    )
    return env


def test_deep_tb_on_cpu_mesh_tier1():
    """Tier-1 (unmarked) deep-tb acceptance: the k=3 and k=4 supersteps
    match k sequential steps AND the fp64 golden oracle on a REAL
    4-device CPU mesh — cross-device width-k ppermutes and shrinking
    mid-ring fills executing, not compile-only — plus the streamk kernel
    (interpret tier) on the same meshes, certifying its domain-edge ring
    pinning distinguishes interior shards from domain edges. Focused
    subprocess (4 devices) so it fits the tier-1 budget; the full
    8-device battery stays @slow."""
    env = _cpu_mesh_env(4)
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(HERE, "multidevice_checks.py"),
            "deep_tb",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"deep-tb multidevice check failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}"
    )
    assert "deep_tb_tier1 OK" in proc.stdout
    assert "deep_tb_streamk_interpret OK" in proc.stdout


@pytest.mark.slow
def test_multidevice_checks_on_cpu_mesh():
    env = _cpu_mesh_env(8)
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "multidevice_checks.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"multidevice checks failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    assert "ALL MULTIDEVICE CHECKS PASSED" in proc.stdout


@pytest.mark.slow
@pytest.mark.parametrize(
    "ndev,mesh,kind,dtype,fused",
    [
        (64, (4, 4, 4), "27pt", "fp32", False),   # judged config 4 topology
        (128, (8, 4, 4), "7pt", "bf16", False),   # judged config 5 topology
        # the 3D fused-DMA route's glue at the judged topologies, via its
        # XLA reference contract (interpret cannot RDMA on multi-axis
        # meshes): landed-ghost face seeding + y/z shell patches execute
        # over 64/128 real mesh devices
        (64, (4, 4, 4), "27pt", "fp32", True),
        (128, (8, 4, 4), "7pt", "bf16", True),
    ],
)
def test_judged_pod_topology_executes(ndev, mesh, kind, dtype, fused):
    """EXECUTE (not just lower) the judged pod decompositions: a full
    distributed step over 64/128 virtual CPU devices at tiny scale must
    match the same grid run undecomposed. Upgrades configs 4-5 from
    compile-only evidence (docs/LOWERING.md) to executed evidence —
    bounded by host memory only because the blocks are tiny. ``fused``
    arms dispatch the 3D fused-DMA route (reference-emulated) instead of
    the default step."""
    env = _cpu_mesh_env(ndev)
    if fused:
        env["HEAT3D_DIRECT_INTERPRET"] = "1"
    code = f"""
import jax, jax.numpy as jnp, numpy as np
from heat3d_tpu.core.config import (BoundaryCondition, GridConfig,
    MeshConfig, Precision, SolverConfig, StencilConfig)
from heat3d_tpu.parallel.step import make_step_fn
from heat3d_tpu.parallel.topology import build_mesh, field_sharding

fused = {fused!r}
mesh_shape = {mesh!r}
grid = tuple(4 * m for m in mesh_shape)
prec = Precision.bf16() if {dtype!r} == "bf16" else Precision.fp32()
host = np.random.default_rng(0).standard_normal(grid).astype(np.float32)

outs = {{}}
for shape in (mesh_shape, (1, 1, 1)):
    on_route = fused and shape != (1, 1, 1)
    cfg = SolverConfig(grid=GridConfig(shape=grid),
        stencil=StencilConfig(kind={kind!r}, bc=BoundaryCondition.PERIODIC),
        mesh=MeshConfig(shape=shape), precision=prec,
        backend="auto" if on_route else "jnp",
        halo="dma" if on_route else "ppermute", overlap=on_route)
    if on_route:
        from heat3d_tpu.parallel.step import _fused_dma_3d_fn
        assert _fused_dma_3d_fn(cfg) is not None, "fused 3D route must dispatch"
    m = build_mesh(cfg.mesh, devices=jax.devices()[: cfg.mesh.num_devices])
    step = jax.jit(make_step_fn(cfg, m, with_residual=True))
    u = jax.device_put(jnp.asarray(host, jnp.dtype(prec.storage)),
                       field_sharding(m, cfg.mesh))
    un, r = jax.block_until_ready(step(u))
    outs[shape] = (np.asarray(un.astype(jnp.float32)), float(r))

got, r_got = outs[mesh_shape]
want, r_want = outs[(1, 1, 1)]
if fused:
    # the fused route's ghost-stack assembly associates adds differently
    # from the exchange path's padded concatenate before the one
    # storage-dtype round-off: FMA-rounding at fp32; at bf16 a 1-ulp
    # disagreement can be a relative difference up to 2^-7 low in a
    # binade, so the bound must cover it at every magnitude (8e-3, the
    # 2-ulp convention of the tb=2 ring check)
    tol = 8e-3 if {dtype!r} == "bf16" else 1e-5
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)
    # residual: a sum of squared per-element 1-ulp disagreements —
    # tiered with the value tolerance, not the bitwise arms' bound
    np.testing.assert_allclose(
        r_got, r_want, rtol=1e-4 if {dtype!r} == "bf16" else 1e-5
    )
else:
    np.testing.assert_array_equal(got, want)  # same math, same op order
    np.testing.assert_allclose(r_got, r_want, rtol=1e-5)
print(f"POD TOPOLOGY OK: {{mesh_shape}} over {ndev} devices == (1,1,1)")
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"pod-topology check failed\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr}"
    )
    assert "POD TOPOLOGY OK" in proc.stdout
