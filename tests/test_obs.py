"""Observability-layer tests (tier-1, CPU): the run ledger records spans
and events with schema-valid nesting, the metrics registry exports
Prometheus/JSON snapshots, the ledger lint catches seeded defects, the
obs CLI reconstructs step-latency percentiles that match the run's own
numbers, fault injection is itself observable, and the satellite fixes
(narrowed logging filter, per-backend RTT cache, summarize_trace
aggregation) cannot regress."""

import json
import os
import sys
from types import SimpleNamespace

import pytest

from heat3d_tpu import obs
from heat3d_tpu.core.config import GridConfig, SolverConfig
from heat3d_tpu.obs import check as ledger_check
from heat3d_tpu.obs.ledger import Ledger
from heat3d_tpu.obs.metrics import MetricsRegistry

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _isolated_obs():
    """Every test starts and ends with no active ledger (the module-level
    singleton would otherwise leak spans across tests)."""
    obs.deactivate()
    yield
    obs.deactivate()


def _read(path):
    return [json.loads(ln) for ln in open(path) if ln.strip()]


# ---- ledger -------------------------------------------------------------


def test_ledger_events_spans_and_context(tmp_path):
    p = str(tmp_path / "led.jsonl")
    led = obs.activate(p, meta={"entry": "test"})
    assert obs.get() is led and led.active
    led.set_context(generation=4)
    led.event("run_start", grid=[8, 8, 8])
    with led.span("outer", steps=2) as sp:
        with led.span("inner"):
            pass
        sp.add(note="x")
    assert sp.dur_s is not None and sp.dur_s >= 0
    with pytest.raises(RuntimeError, match="boom"):
        with led.span("fails"):
            raise RuntimeError("boom")
    obs.deactivate(rc=0)

    evs = _read(p)
    names = [e["event"] for e in evs]
    assert names == [
        "ledger_open", "run_start", "inner", "outer", "fails", "ledger_close",
    ]
    # envelope on every event; context tag on everything after set_context
    for e in evs:
        for f in ("ts", "run_id", "proc", "seq", "event", "kind"):
            assert f in e
    assert all(e["generation"] == 4 for e in evs[1:])
    assert len({e["run_id"] for e in evs}) == 1
    outer = evs[names.index("outer")]
    inner = evs[names.index("inner")]
    # spans written at close: child precedes parent, bounds nest
    assert inner["seq"] < outer["seq"]
    assert outer["t0"] <= inner["t0"] and inner["t1"] <= outer["t1"]
    assert outer["depth"] == 0 and inner["depth"] == 1
    assert outer["note"] == "x" and outer["steps"] == 2
    failed = evs[names.index("fails")]
    assert failed["status"] == "error" and "boom" in failed["error"]
    # the freshly generated ledger passes its own lint (and the script
    # wrapper agrees — the CI gate and the library cannot drift)
    assert ledger_check.check_file(p) == []
    assert ledger_check.main([p]) == 0


def test_ledger_null_when_unconfigured_and_env_activation(tmp_path, monkeypatch):
    monkeypatch.delenv("HEAT3D_LEDGER", raising=False)
    led = obs.get()
    assert not led.active
    led.event("ignored")
    with led.span("ignored") as sp:
        pass
    assert sp.dur_s is not None  # null spans still time (callers use dur_s)

    p = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("HEAT3D_LEDGER", p)
    obs.deactivate()  # re-arm env detection
    led2 = obs.get()
    assert led2.active
    led2.event("hello")
    obs.deactivate()
    assert [e["event"] for e in _read(p)] == ["ledger_open", "hello",
                                             "ledger_close"]


def test_ledger_unserializable_span_field_salvaged_schema_valid(tmp_path):
    """A span field json cannot serialize (circular dict) is dropped, not
    the whole record's span fields — the salvage record must still pass
    the project's own lint (it gates the bench suite's rc)."""
    p = str(tmp_path / "l.jsonl")
    led = Ledger(p)
    circular: dict = {}
    circular["self"] = circular
    with led.span("chunk", steps=2, bad=circular):
        pass
    led.event("point_bad", bad=circular, fine=1)
    led.close()
    evs = _read(p)
    chunk = next(e for e in evs if e["event"] == "chunk")
    assert chunk["kind"] == "span" and chunk["status"] == "ok"
    assert all(f in chunk for f in ("t0", "t1", "dur_s", "depth"))
    assert chunk["malformed_fields"] == ["bad"] and chunk["steps"] == 2
    pt = next(e for e in evs if e["event"] == "point_bad")
    assert pt["fine"] == 1 and "bad" not in pt
    assert ledger_check.check_file(p) == []


def test_metrics_export_unwritable_path_does_not_raise(tmp_path, monkeypatch):
    """export_at_exit on an unwritable HEAT3D_METRICS path logs and
    returns None — telemetry must not turn a completed run into a
    nonzero exit."""
    from heat3d_tpu.obs.metrics import export_at_exit

    # a FILE where a parent directory is needed fails for every uid
    # (root ignores directory modes, so chmod-based fixtures skip there)
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    monkeypatch.setenv("HEAT3D_METRICS", str(blocker / "m.json"))
    assert export_at_exit() is None


def test_check_ledger_start_line_scopes_report(tmp_path):
    """--start-line hides historical defects from APPEND resume sessions
    (full-file context still parsed) — same contract as
    check_provenance.py's scoping."""
    p = str(tmp_path / "l.jsonl")
    _write_ledger(p, [
        _envelope(0, "orphan", run_id="dead"),     # historical defect
        _envelope(0, "ledger_open", run_id="r9"),
        _envelope(1, "fine", run_id="r9"),
    ])
    assert ledger_check.main([p]) == 1
    assert ledger_check.main(["--start-line", "2", p]) == 0


def test_ledger_envelope_fields_never_clobbered(tmp_path):
    led = Ledger(str(tmp_path / "l.jsonl"))
    led.event("x", seq=999, run_id="fake", kind="span")
    led.close()
    evs = _read(led.path)
    x = evs[1]
    assert x["seq"] == 1 and x["run_id"] == led.run_id and x["kind"] == "point"


def test_ledger_fails_soft_never_kills_the_run(tmp_path, capsys):
    """Telemetry must not kill the run it observes: an unwritable path
    fails soft at activation (NULL ledger + stderr note), and a write
    error mid-run disables the ledger instead of raising."""
    blocker = tmp_path / "f"
    blocker.write_text("")
    led = obs.activate(str(blocker / "led.jsonl"))  # parent is a FILE
    assert not led.active
    led.event("still_fine")  # no-op, no raise
    assert "running without one" in capsys.readouterr().err

    p = str(tmp_path / "l.jsonl")
    led2 = Ledger(p)
    led2.event("before")
    real_f = led2._f

    def die(_):
        raise OSError(28, "No space left on device")

    led2._f = SimpleNamespace(
        closed=False, write=die, flush=lambda: None, close=real_f.close
    )
    led2.event("after")  # must not raise; ledger disables itself
    assert "disabled" in capsys.readouterr().err
    led2._f = real_f  # the real (now closed) file: later events drop
    led2.event("later")
    led2.close()
    assert [e["event"] for e in _read(p)] == ["ledger_open", "before"]


# ---- metrics registry ---------------------------------------------------


def test_metrics_counter_gauge_histogram_and_snapshot():
    reg = MetricsRegistry()
    c = reg.counter("retries_total", "help text")
    c.inc()
    c.inc(2, reason="deadline")
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("rtt_seconds")
    g.set(0.075, backend="tpu")
    h = reg.histogram("lat_seconds")
    for v in [1.0, 2.0, 3.0, 4.0, 100.0]:
        h.observe(v)
    snap = reg.snapshot()
    assert snap["heat3d_retries_total"]["values"][""] == 1
    assert snap["heat3d_retries_total"]["values"]['{reason="deadline"}'] == 2
    st = snap["heat3d_lat_seconds"]["values"][""]
    assert st["count"] == 5 and st["min"] == 1.0 and st["max"] == 100.0
    assert st["p50"] == 3.0 and st["p95"] == 100.0
    # same-name different-type registration is a bug, not a silent alias
    with pytest.raises(TypeError):
        reg.gauge("retries_total")


def test_metrics_prometheus_text_and_files(tmp_path):
    reg = MetricsRegistry()
    reg.counter("a_total", "things").inc(3)
    reg.histogram("lat_seconds").observe(2.0)
    text = reg.to_prometheus_text()
    assert "# TYPE heat3d_a_total counter" in text
    assert "heat3d_a_total 3.0" in text
    assert "# TYPE heat3d_lat_seconds summary" in text
    assert 'heat3d_lat_seconds{quantile="0.5"} 2.0' in text
    prom = tmp_path / "m.prom"
    reg.write_snapshot(str(prom))
    assert prom.read_text() == text
    js = tmp_path / "m.json"
    reg.write_snapshot(str(js))
    assert json.loads(js.read_text())["heat3d_a_total"]["kind"] == "counter"


def test_histogram_cap_marks_clipped():
    from heat3d_tpu.obs.metrics import HISTOGRAM_SAMPLE_CAP

    reg = MetricsRegistry()
    h = reg.histogram("big")
    for i in range(HISTOGRAM_SAMPLE_CAP + 10):
        h.observe(float(i))
    st = h.stats()
    assert st["count"] == HISTOGRAM_SAMPLE_CAP + 10
    assert st["clipped"] is True


# ---- ledger lint --------------------------------------------------------


def _envelope(seq, event="e", kind="point", run_id="r1", **extra):
    rec = {
        "ts": 100.0 + seq, "run_id": run_id, "proc": 0, "seq": seq,
        "event": event, "kind": kind,
    }
    rec.update(extra)
    return rec


def _write_ledger(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_check_ledger_catches_seeded_defects(tmp_path):
    p = str(tmp_path / "bad.jsonl")
    span = dict(t0=1.0, t1=2.0, dur_s=1.0, depth=0, status="ok")
    _write_ledger(p, [
        _envelope(0, "ledger_open"),
        _envelope(1, "ok_span", kind="span", **span),
        {k: v for k, v in _envelope(2).items() if k != "run_id"},  # missing
        _envelope(3, kind="bogus"),                    # bad kind
        _envelope(4, "torn", kind="span", t0=5.0, t1=4.0, dur_s=-1.0,
                  depth=0, status="ok"),               # ends before start
        _envelope(2),                                  # seq regression
        _envelope(90, "orphan", run_id="r2"),          # no ledger_open
    ])
    msgs = [m for _, m in ledger_check.check_file(p)]
    assert any("missing required field 'run_id'" in m for m in msgs)
    assert any("not 'point' or 'span'" in m for m in msgs)
    assert any("ends before it starts" in m for m in msgs)
    assert any("not above seq" in m for m in msgs)
    assert any("no ledger_open" in m for m in msgs)
    assert ledger_check.main([p]) == 1


def test_check_ledger_span_nesting_rule(tmp_path):
    def spans(path, bounds):
        recs = [_envelope(0, "ledger_open")]
        for i, (t0, t1) in enumerate(bounds, start=1):
            recs.append(_envelope(
                i, f"s{i}", kind="span", t0=t0, t1=t1, dur_s=t1 - t0,
                depth=0, status="ok",
            ))
        _write_ledger(path, recs)

    ok = str(tmp_path / "nested.jsonl")
    # disjoint, contained, deeper-contained: a proper laminar family
    spans(ok, [(1.0, 2.0), (2.5, 6.0), (3.0, 4.0), (3.2, 3.8)])
    assert ledger_check.check_file(ok) == []

    bad = str(tmp_path / "overlap.jsonl")
    spans(bad, [(1.0, 3.0), (2.0, 4.0)])  # partial overlap
    msgs = [m for _, m in ledger_check.check_file(bad)]
    assert any("partially overlaps" in m for m in msgs)


def test_check_ledger_script_wrapper_on_fresh_ledger(tmp_path):
    """Satellite: the scripts/check_ledger.py entry point (the thing
    run_bench_suite.sh invokes) passes on a freshly generated ledger and
    fails on a torn one."""
    import subprocess

    p = str(tmp_path / "fresh.jsonl")
    led = obs.activate(p)
    with led.span("steps", steps=4):
        pass
    led.event("run_summary", steps=4)
    obs.deactivate(rc=0)

    script = os.path.join(REPO, "scripts", "check_ledger.py")
    r = subprocess.run(
        [sys.executable, script, p], capture_output=True, text=True,
        cwd=REPO, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    with open(p, "a") as f:
        f.write('{"event": "torn"}\n')
    r2 = subprocess.run(
        [sys.executable, script, p], capture_output=True, text=True,
        cwd=REPO, timeout=120,
    )
    assert r2.returncode == 1


# ---- obs CLI ------------------------------------------------------------


def test_obs_cli_summary_tail_check(tmp_path, capsys):
    from heat3d_tpu.cli import main as heat3d_main

    p = str(tmp_path / "led.jsonl")
    led = obs.activate(p)
    led.event("run_start", grid=[8, 8, 8])
    for n in (4, 4, 2):
        with led.span("steps", steps=n):
            pass
    obs.deactivate()

    assert heat3d_main(["obs", "check", p]) == 0
    capsys.readouterr()
    assert heat3d_main(["obs", "summary", p]) == 0
    out = capsys.readouterr().out
    assert "run_start" in out and "steps" in out
    assert "step latency" in out
    assert heat3d_main(["obs", "tail", p, "-n", "2"]) == 0
    tail = capsys.readouterr().out
    assert len(tail.strip().splitlines()) == 2


def test_obs_cli_step_latency_reconstruction(tmp_path, capsys):
    """p50/p95 from span records with known durations: one sample per
    span, dur/steps — the documented reconstruction rule."""
    from heat3d_tpu.obs.cli import step_latencies

    events = [
        {"kind": "span", "event": "chunk", "status": "ok", "steps": 4,
         "dur_s": 0.4},
        {"kind": "span", "event": "chunk", "status": "ok", "steps": 2,
         "dur_s": 0.1},
        {"kind": "span", "event": "chunk", "status": "error", "steps": 4,
         "dur_s": 9.9},   # failed chunk: excluded
        {"kind": "span", "event": "ckpt_save", "status": "ok",
         "dur_s": 1.0},   # not a step span
        {"kind": "point", "event": "chunk", "steps": 4},
    ]
    lats = step_latencies(events)
    assert lats == [0.1, 0.05]


# ---- instrumented subsystems -------------------------------------------


def test_retry_policy_writes_ledger_events(tmp_path):
    from heat3d_tpu.resilience.retry import RetryPolicy

    p = str(tmp_path / "led.jsonl")
    obs.activate(p)
    calls = {"n": 0}

    def attempt():
        calls["n"] += 1
        return "up" if calls["n"] >= 3 else None

    policy = RetryPolicy(max_attempts=5, base_delay_s=0.0, max_delay_s=0.0)
    outcome = policy.run(attempt)
    obs.deactivate()
    assert outcome.ok
    evs = _read(p)
    attempts = [e for e in evs if e["event"] == "retry_attempt"]
    outcomes = [e for e in evs if e["event"] == "retry_outcome"]
    assert len(attempts) == 3
    assert [a["ok"] for a in attempts] == [False, False, True]
    assert outcomes[-1]["stop_reason"] == "success"


def test_fault_injection_is_observable(tmp_path):
    """Satellite: every fired fault leaves a fault_injected ledger event
    — asserting observability of the injection itself."""
    from heat3d_tpu.resilience.faults import FaultPlan, InjectedBackendLoss, _parse_spec

    p = str(tmp_path / "led.jsonl")
    obs.activate(p)
    plan = FaultPlan(_parse_spec("backend-loss:step=8:down=1"))
    plan.on_step(4)  # below the trigger: no event
    with pytest.raises(InjectedBackendLoss):
        plan.on_step(8)
    plan.on_step(9)  # one-shot: no second event
    obs.deactivate()
    faults = [e for e in _read(p) if e["event"] == "fault_injected"]
    assert len(faults) == 1
    assert faults[0]["kind_"] == "backend-loss"
    assert faults[0]["step"] == 8
    assert faults[0]["params"] == {"step": 8, "down": 1}


def test_checkpoint_save_load_quarantine_events(tmp_path):
    from heat3d_tpu.models.heat3d import HeatSolver3D
    from heat3d_tpu.resilience.faults import corrupt_one_shard
    from heat3d_tpu.utils import checkpoint as ckpt

    p = str(tmp_path / "led.jsonl")
    obs.activate(p)
    obs.REGISTRY.reset()
    solver = HeatSolver3D(SolverConfig(grid=GridConfig.cube(8), backend="jnp"))
    u = solver.init_state("hot-cube")
    ck = str(tmp_path / "ck")
    solver.save_checkpoint(ck, u, 3)
    solver.load_checkpoint(ck)
    corrupt_one_shard(ck)
    with pytest.raises(ckpt.ShardCorruptError):
        solver.load_checkpoint(ck)
    ckpt.quarantine(ck, reason="test")
    obs.deactivate()
    evs = _read(p)
    names = [e["event"] for e in evs]
    assert "ckpt_save" in names and "ckpt_load" in names
    assert "ckpt_corrupt" in names and "ckpt_quarantine" in names
    saves = [e for e in evs if e["event"] == "ckpt_save"]
    assert saves[0]["status"] == "ok" and saves[0]["step"] == 3
    assert saves[0]["shards"] >= 1 and saves[0]["bytes"] > 0
    loads = [e for e in evs if e["event"] == "ckpt_load"]
    assert loads[0]["status"] == "ok" and loads[-1]["status"] == "error"
    snap = obs.REGISTRY.snapshot()
    assert snap["heat3d_ckpt_writes_total"]["values"][""] == 1
    verify = snap["heat3d_ckpt_verify_total"]["values"]
    assert verify['{result="ok"}'] >= 1
    assert verify['{result="corrupt"}'] == 1
    assert snap["heat3d_ckpt_quarantine_total"]["values"][""] == 1
    assert ledger_check.check_file(p) == []


def test_bench_rows_carry_sync_rtt_and_land_in_ledger(tmp_path):
    from heat3d_tpu.bench.harness import bench_halo, bench_throughput

    p = str(tmp_path / "led.jsonl")
    obs.activate(p)
    cfg = SolverConfig(grid=GridConfig.cube(16), backend="jnp")
    t = bench_throughput(cfg, steps=2, warmup=1, repeats=1)
    h = bench_halo(cfg, iters=3, warmup=1)
    obs.deactivate()
    assert isinstance(t["sync_rtt_s"], float)
    assert isinstance(h["sync_rtt_s"], float)
    rows = [e for e in _read(p) if e["event"] == "bench_row"]
    assert {r["bench"] for r in rows} == {"throughput", "halo"}
    # the row's UTC measurement timestamp survives the envelope collision
    # as ts_ — the join key back to bench_results.jsonl
    assert all(r["ts_"] in (t["ts"], h["ts"]) for r in rows)
    # ... and the fresh rows pass the extended provenance lint
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_provenance", os.path.join(REPO, "scripts", "check_provenance.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert not mod.check_row(t), mod.check_row(t)
    assert not mod.check_row(h), mod.check_row(h)


# ---- satellite: sync_overhead per-backend cache -------------------------


def test_sync_overhead_cached_per_backend(monkeypatch):
    from heat3d_tpu.utils import timing

    timing.reset_sync_overhead_cache()
    calls = {"n": 0}
    real_force_sync = timing.force_sync

    def counting_force_sync(x):
        calls["n"] += 1
        return real_force_sync(x)

    monkeypatch.setattr(timing, "force_sync", counting_force_sync)
    r1 = timing.sync_overhead(samples=2)
    n_after_first = calls["n"]
    assert n_after_first == 3  # 1 warm + 2 samples
    r2 = timing.sync_overhead(samples=2)
    assert r2 == r1
    assert calls["n"] == n_after_first  # cached: no new syncs
    r3 = timing.sync_overhead(samples=2, refresh=True)
    assert calls["n"] == 2 * n_after_first
    assert isinstance(r3, float)
    # the measured RTT is published as a per-backend gauge
    import jax

    g = obs.REGISTRY.gauge("sync_rtt_seconds")
    assert g.value(backend=jax.default_backend()) is not None
    timing.reset_sync_overhead_cache()


# ---- satellite: narrowed _Process0Filter --------------------------------


def test_process0_filter_narrowed_exceptions(monkeypatch):
    import logging as pylogging

    from heat3d_tpu.utils.logging import _Process0Filter

    f = _Process0Filter()
    rec = pylogging.LogRecord("n", pylogging.INFO, "p", 1, "m", (), None)
    warn = pylogging.LogRecord("n", pylogging.WARNING, "p", 1, "m", (), None)
    assert f.filter(warn) is True  # WARNING+ always passes

    # expected failures (backend state not queryable) assume-coordinator
    import jax._src.xla_bridge as xb

    monkeypatch.setattr(
        xb, "backends_are_initialized",
        lambda: (_ for _ in ()).throw(RuntimeError("not ready")),
    )
    assert f.filter(rec) is True

    # an UNEXPECTED failure must propagate — the bare-except bug this
    # satellite fixes would have silently returned True here
    monkeypatch.setattr(
        xb, "backends_are_initialized",
        lambda: (_ for _ in ()).throw(ValueError("real bug")),
    )
    with pytest.raises(ValueError, match="real bug"):
        f.filter(rec)


# ---- satellite: summarize_trace aggregation ----------------------------


def _load_summarize_trace():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "summarize_trace", os.path.join(REPO, "scripts", "summarize_trace.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ev(metadata_id, duration_ps):
    return SimpleNamespace(metadata_id=metadata_id, duration_ps=duration_ps)


def test_summarize_trace_single_line_aggregation_rule():
    """The double-count fix: a plane carries several lines covering the
    SAME wall time; exactly ONE is aggregated — the op-level line when
    present, else the busiest."""
    mod = _load_summarize_trace()
    meta = {
        1: SimpleNamespace(name="fusion.1"),
        2: SimpleNamespace(name="heat3d.stencil/fusion.2"),
        3: SimpleNamespace(name="heat3d.stencil/heat3d.halo_exchange/ppermute.3"),
    }
    ops_line = SimpleNamespace(
        name="XLA Ops", events=[_ev(1, 2e6), _ev(2, 3e6), _ev(2, 1e6),
                                _ev(3, 4e6)]
    )
    module_line = SimpleNamespace(
        name="XLA Modules", events=[_ev(1, 10e6)]  # same wall time, coarser
    )
    steps_line = SimpleNamespace(name="Steps", events=[_ev(1, 10e6)])

    picked = mod.pick_line([module_line, ops_line, steps_line])
    assert picked is ops_line  # the op-level line wins over busier lines

    totals, counts = mod.aggregate_line(picked, meta)
    assert totals["heat3d.stencil/fusion.2"] == pytest.approx(4.0)  # us
    assert counts["heat3d.stencil/fusion.2"] == 2
    # the sum is ONE line's time, not all lines' (no double count)
    assert sum(totals.values()) == pytest.approx(10.0)

    # without an op line, the busiest line is aggregated
    assert mod.pick_line([module_line, steps_line]) in (module_line, steps_line)

    # phase attribution groups by the INNERMOST heat3d scope
    phases = mod.phase_totals(totals)
    assert phases["heat3d.stencil"] == pytest.approx(4.0)
    assert phases["heat3d.halo_exchange"] == pytest.approx(4.0)
    assert phases["(unattributed)"] == pytest.approx(2.0)
    # a host-plane python frame naming the FILE heat3d.py is not a phase
    assert mod.phase_name("$heat3d.py:301 run") is None
    assert mod.phase_name("heat3d.warmup") == "heat3d.warmup"
    # dotted sub-phases survive whole (the per-axis halo scopes), and the
    # innermost-token rule still applies across nested scopes; XLA's ".N"
    # op suffixes are not swallowed into the phase
    assert (
        mod.phase_name("heat3d.halo_exchange/heat3d.halo.x/ppermute.3")
        == "heat3d.halo.x"
    )
    assert mod.phase_name("heat3d.stencil/fusion.2") == "heat3d.stencil"


def test_summarize_trace_synthetic_xspace_proto(tmp_path, capsys):
    """Satellite, real-proto tier (skips when xplane_pb2 is absent — the
    duck-typed tests above cover the logic either way): a synthetic
    XSpace with an op line AND a same-wall-time module line summarizes to
    the op line's total only — the double-count fix."""
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2"
    )
    mod = _load_summarize_trace()
    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    plane.event_metadata[1].id = 1
    plane.event_metadata[1].name = "heat3d.stencil/fusion.1"
    plane.event_metadata[2].id = 2
    plane.event_metadata[2].name = "whole-module"
    ops = plane.lines.add()
    ops.name = "XLA Ops"
    ev = ops.events.add()
    ev.metadata_id = 1
    ev.duration_ps = int(7e6)  # 7 us
    mods = plane.lines.add()
    mods.name = "XLA Modules"
    ev2 = mods.events.add()
    ev2.metadata_id = 2
    ev2.duration_ps = int(7e6)  # same wall time, coarser granularity
    path = tmp_path / "t.xplane.pb"
    path.write_bytes(xs.SerializeToString())

    assert mod.summarize(str(path)) == 0
    out = capsys.readouterr().out
    assert "[line: XLA Ops]" in out
    assert "total 0.01 ms" in out  # 7 us, once — not 14 (double-counted)
    assert "heat3d.stencil" in out


def test_summarize_trace_plane_renders(capsys):
    mod = _load_summarize_trace()
    meta = {1: SimpleNamespace(name="heat3d.residual/reduce.1")}
    plane = SimpleNamespace(
        name="/device:TPU:0",
        lines=[SimpleNamespace(name="XLA Ops", events=[_ev(1, 5e6)])],
        event_metadata=meta,
    )
    mod.summarize_plane(plane)
    out = capsys.readouterr().out
    assert "heat3d.residual" in out and "by heat3d phase" in out


# ---- the acceptance criterion ------------------------------------------


def test_e2e_supervised_fault_ledger_reconstruction(tmp_path, monkeypatch):
    """End-to-end CPU acceptance: a supervised run with an injected
    backend loss (HEAT3D_FAULTS) produces a schema-valid ledger holding
    step spans, the fault event, retry attempts, the generation
    transitions, and checkpoint write/verify records — and `heat3d obs
    summary`'s reconstructed step-latency p50/p95 agree with the run's
    own metrics-registry numbers within 20%."""
    from heat3d_tpu.models.heat3d import HeatSolver3D
    from heat3d_tpu.obs.cli import read_ledger, step_latencies
    from heat3d_tpu.resilience.faults import FaultPlan
    from heat3d_tpu.resilience.retry import RetryPolicy
    from heat3d_tpu.resilience.supervisor import run_supervised

    monkeypatch.setenv("HEAT3D_FAULTS", "backend-loss:step=8:down=2")
    monkeypatch.delenv("HEAT3D_FAULT_STATE", raising=False)
    p = str(tmp_path / "led.jsonl")
    obs.activate(p, meta={"entry": "e2e"})
    obs.REGISTRY.reset()
    fast = RetryPolicy(
        base_delay_s=0.01, multiplier=1.5, max_delay_s=0.05, deadline_s=5.0
    )
    solver = HeatSolver3D(SolverConfig(grid=GridConfig.cube(8), backend="jnp"))
    res = run_supervised(
        solver, 12, str(tmp_path / "ck"), checkpoint_every=4,
        heal_policy=fast, probe=lambda: "cpu",
        faults=FaultPlan.from_env(),
    )
    metrics = obs.REGISTRY.snapshot()
    obs.get().event("metrics_summary", metrics=metrics)
    obs.deactivate(rc=0)

    assert res.steps_done == 12 and len(res.recoveries) == 1

    # 1. schema-valid
    assert ledger_check.check_file(p) == [], ledger_check.check_file(p)

    evs = read_ledger(p)
    names = [e["event"] for e in evs]
    # 2. step spans (the supervised chunks), including the one the fault
    # killed (status=error)
    chunks = [e for e in evs if e["event"] == "chunk"]
    assert len(chunks) == 4  # 0-4, 4-8, 8-FAIL, 8-12(rewound), 8-12... 3 ok
    assert [c["status"] for c in chunks].count("error") == 1
    # 3. the fault event, 4. retry attempts, 5. generation transitions,
    # 6. checkpoint writes + verified loads
    fault = next(e for e in evs if e["event"] == "fault_injected")
    assert fault["kind_"] == "backend-loss" and fault["step"] == 8
    retries = [e for e in evs if e["event"] == "retry_attempt"]
    assert len(retries) >= 3  # 2 injected down-probes + the heal
    gens = [e for e in evs if e["event"] == "generation_save"]
    assert [g["step"] for g in gens] == [4, 8, 12]
    assert "ckpt_save" in names and "ckpt_load" in names
    assert (
        metrics["heat3d_ckpt_verify_total"]["values"]['{result="ok"}'] >= 1
    )
    recovery = next(e for e in evs if e["event"] == "recovery")
    assert recovery["resumed_from"] == 8
    # events after the resume carry the generation context tag
    post = [e for e in evs if e.get("generation") == 8]
    assert any(e["event"] == "generation_save" and e["step"] == 12
               for e in post)

    # 7. obs-summary reconstruction vs the run's own numbers: identical
    # inputs (ok-chunk dur/steps), so well within the 20% criterion
    lats = step_latencies(evs)
    assert len(lats) == 3
    from heat3d_tpu.obs.metrics import percentile

    run_stats = metrics["heat3d_step_latency_seconds"]["values"][""]
    for q, key in ((50, "p50"), (95, "p95")):
        rebuilt = percentile(lats, q)
        own = run_stats[key]
        assert abs(rebuilt - own) <= 0.2 * own, (q, rebuilt, own)

    # ... and the obs CLI renders it without error
    from heat3d_tpu.obs.cli import main as obs_main

    assert obs_main(["summary", p]) == 0
    assert obs_main(["check", p]) == 0


def test_cli_run_writes_ledger_and_metrics_export(tmp_path, monkeypatch):
    """The solver CLI entry point: --ledger produces a lint-clean ledger
    with run_start/run_loop/run_summary/metrics_summary, and
    HEAT3D_METRICS exports a snapshot file at exit."""
    from heat3d_tpu.cli import main as heat3d_main

    p = str(tmp_path / "led.jsonl")
    prom = str(tmp_path / "m.prom")
    monkeypatch.setenv("HEAT3D_METRICS", prom)
    rc = heat3d_main([
        "--grid", "8", "--steps", "4", "--backend", "jnp", "--ledger", p,
    ])
    assert rc == 0
    assert ledger_check.check_file(p) == []
    evs = _read(p)
    names = [e["event"] for e in evs]
    for want in ("ledger_open", "run_start", "warmup", "run_loop",
                 "run_summary", "metrics_summary", "ledger_close"):
        assert want in names, (want, names)
    loop = next(e for e in evs if e["event"] == "run_loop")
    assert loop["steps"] == 4 and loop["status"] == "ok"
    summary = next(e for e in evs if e["event"] == "run_summary")
    assert summary["steps"] == 4 and "gcell_updates_per_sec" in summary
    close = next(e for e in evs if e["event"] == "ledger_close")
    assert close["rc"] == 0
    text = open(prom).read()
    assert "heat3d_step_latency_seconds" in text


def test_summarize_trace_promotion_wrapper_reexports():
    """ISSUE 8 satellite: the script is now a thin wrapper over the
    promoted core in heat3d_tpu/obs/perf/timeline.py — same helpers,
    same objects (the duck-typed tests above exercise them THROUGH the
    wrapper, so the promotion cannot drift silently)."""
    mod = _load_summarize_trace()
    from heat3d_tpu.obs.perf import timeline

    for name in ("pick_line", "aggregate_line", "phase_name",
                 "phase_totals", "summarize", "summarize_plane",
                 "find_xplane", "PHASE_RE"):
        assert getattr(mod, name) is getattr(timeline, name)
    assert mod.main is timeline.summarize_trace_main
