#!/usr/bin/env python
"""Weak-scaling + chaos harness — the numbers the paper's >=90%
weak-scaling claim is judged against, with elastic degradation measured
in the same session (docs/RESILIENCE.md "Elastic degradation";
docs/POD_RUNBOOK.md "Chaos drill").

Per mesh rung the grid GROWS with the mesh (constant local block), and
the harness reports per-chip Gcell/s, the halo share of the step's
compiled byte traffic (the roofline model's denominator), and the
weak-scaling efficiency vs the 1-chip rung. With ``--chaos keep=K`` the
largest rung additionally runs SUPERVISED with an injected
partial-device-loss mid-run (resilience/faults.py) under
``heal_mode=elastic``: the run re-factorizes onto the K survivors,
finishes degraded, and the harness reports recovery time (heal wait +
re-stitch, from the ledger's ``elastic_refactor`` event) and
post-degradation throughput as a second, ``post_heal: true`` row.

Rows are JSONL (``bench: "weak_scaling"``), lint-enforced by
``scripts/check_provenance.py``: every row carries ``ts``, ``platform``,
``mesh_shape`` and a boolean ``post_heal`` — degraded throughput can
never pollute the scaling record unlabeled. The session ledger
(``--ledger`` / ``$HEAT3D_LEDGER``) carries the full event stream;
``heat3d obs summary`` prints the elastic section, ``heat3d obs
timeline`` attributes the outage.

Usage (CPU smoke — the same matrix the pod session runs bigger)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \\
      python scripts/weak_scaling.py --local 16 \\
      --meshes 1x1x1,2x1x1,4x1x1 --steps 20 --chaos keep=2 \\
      --out weak_scaling.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def parse_meshes(spec: str):
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        dims = tuple(int(x) for x in tok.lower().split("x"))
        if len(dims) != 3 or any(d < 1 for d in dims):
            raise ValueError(f"--meshes entry {tok!r} (want PxQxR)")
        out.append(dims)
    if not out:
        raise ValueError("--meshes: no rungs")
    return out


def parse_chaos(spec):
    """``keep=K[,at-frac=F]`` -> (keep, at_frac). None disables chaos."""
    if not spec:
        return None
    keep, at_frac = None, 0.5
    for tok in spec.split(","):
        k, _, v = tok.strip().partition("=")
        if k == "keep":
            keep = int(v)
        elif k == "at-frac":
            at_frac = float(v)
        else:
            raise ValueError(f"--chaos: unknown key {k!r} (keep, at-frac)")
    if keep is None or keep < 1:
        raise ValueError("--chaos needs keep=K >= 1")
    if not 0.0 < at_frac < 1.0:
        raise ValueError("--chaos at-frac must be in (0, 1)")
    return keep, at_frac


def halo_share_model(solver) -> float:
    """Halo bytes as a fraction of the step's compiled byte traffic per
    exchange period (XLA cost model — the same accounting bench rows and
    the roofline report use). Raises on failure; the caller records
    null (telemetry fails soft, never the rung)."""
    from heat3d_tpu.obs.perf.roofline import halo_cost_fields, step_cost_fields

    step = step_cost_fields(solver)["cost_bytes_per_step"]
    halo = halo_cost_fields(solver.cfg)["cost_bytes_per_step"]
    k = max(1, solver.cfg.time_blocking)
    if not step or not halo:
        raise ValueError("cost model reported no bytes")
    return max(0.0, min(1.0, halo / (step * k)))


def timed_gcell(solver, u, steps: int) -> float:
    """Gcell updates/s of ``steps`` compiled updates (one warmup step
    outside the window, force-synced boundaries — the bench discipline
    at harness scale)."""
    from heat3d_tpu.utils.timing import force_sync

    u = solver.run(u, 1)
    force_sync(u)
    t0 = time.perf_counter()
    u = solver.run(u, steps)
    force_sync(u)
    dt = time.perf_counter() - t0
    return solver.cfg.grid.num_cells * steps / dt / 1e9


def run_rung(cfg, steps: int):
    """One healthy rung: (gcell_per_sec, halo_share|None)."""
    from heat3d_tpu.models.heat3d import HeatSolver3D

    solver = HeatSolver3D(cfg)
    u = solver.init_state("hot-cube")
    rate = timed_gcell(solver, u, steps)
    try:
        share = halo_share_model(solver)
    except Exception as e:  # noqa: BLE001 - model share is telemetry
        print(f"weak_scaling: halo share model unavailable: {e}",
              file=sys.stderr)
        share = None
    return rate, share


def run_chaos_rung(cfg, steps: int, keep: int, at_frac: float,
                   tmp_root: str):
    """The chaos rung: a supervised run losing devices mid-flight under
    heal_mode=elastic. Returns (result, recovery_s, restitch_s,
    degraded_rate)."""
    import jax

    from heat3d_tpu.models.heat3d import HeatSolver3D
    from heat3d_tpu.resilience.faults import FaultPlan, _parse_spec

    loss_step = max(1, int(steps * at_frac))
    ckpt_every = max(1, loss_step // 2)
    plan = FaultPlan(
        _parse_spec(f"partial-device-loss:step={loss_step}:keep={keep}")
    )
    solver = HeatSolver3D(cfg)
    result = solver.run_supervised(
        total_steps=steps,
        ckpt_root=tmp_root,
        checkpoint_every=ckpt_every,
        faults=plan,
        heal_mode="elastic",
        # in-process probe: this harness injects the loss itself, so the
        # backend is genuinely alive — the elastic re-plan (not outage
        # detection) is what's being measured; the out-of-process probe
        # tier has its own tests
        probe=lambda: jax.default_backend(),
        want_platform=jax.default_backend(),
    )
    # the judged recovery time comes from the in-process Recovery
    # records (heal wait + re-stitch) — correct with or without an
    # active ledger, unlike a ledger re-read
    recovery_s = sum(
        r.heal_wait_s + (r.restitch_s or 0.0) for r in result.recoveries
    )
    restitch_s = sum(
        r.restitch_s for r in result.recoveries if r.restitch_s is not None
    )
    # post-degradation throughput: a timed window on the survivor-mesh
    # solver the supervised run finished with
    degraded_rate = timed_gcell(result.solver, result.u, max(4, steps // 4))
    return result, recovery_s, restitch_s, degraded_rate


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--local", type=int, default=32,
                    help="per-chip grid edge (weak scaling: grid = "
                    "local * mesh extent per axis)")
    ap.add_argument("--meshes", default="1x1x1,2x1x1,4x1x1",
                    help="comma-separated mesh rungs, PxQxR each")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--stencil", choices=["7pt", "27pt"], default="7pt")
    ap.add_argument("--dtype", choices=["fp32", "bf16"], default="fp32")
    ap.add_argument("--time-blocking", type=int, default=1)
    ap.add_argument("--chaos", default=None, metavar="keep=K[,at-frac=F]",
                    help="inject a partial device loss on the LARGEST "
                    "rung (supervised, heal_mode=elastic): K devices "
                    "survive, the loss fires at frac F of the step "
                    "budget (default 0.5)")
    ap.add_argument("--out", default="weak_scaling.jsonl",
                    help="JSONL rows (bench: weak_scaling)")
    ap.add_argument("--ledger", default=None,
                    help="run ledger path (default $HEAT3D_LEDGER)")
    ap.add_argument("--ckpt-root", default=None,
                    help="chaos-rung checkpoint directory (default: a "
                    "fresh tempdir)")
    args = ap.parse_args(argv)

    meshes = parse_meshes(args.meshes)
    chaos = parse_chaos(args.chaos)

    # jax import AFTER arg validation: a bad flag fails in ms
    import jax

    from heat3d_tpu import obs
    from heat3d_tpu.core.config import (
        GridConfig,
        MeshConfig,
        Precision,
        SolverConfig,
        StencilConfig,
    )

    obs.activate(args.ledger, meta={"entry": "weak_scaling"})
    platform = jax.default_backend()
    ndev_avail = len(jax.devices())
    # the chaos drill targets the largest rung that will actually RUN
    # (keyed off meshes[-1] alone, a too-big last rung would silently
    # drop the drill the operator asked for)
    chaos_target = None
    if chaos:
        runnable = [
            m for m in meshes
            if 1 < m[0] * m[1] * m[2] <= ndev_avail
            and chaos[0] < m[0] * m[1] * m[2]
        ]
        chaos_target = runnable[-1] if runnable else None
        if chaos_target is None:
            print(
                f"weak_scaling: --chaos keep={chaos[0]} has no runnable "
                f"multi-device rung (have {ndev_avail} device(s)) — the "
                "chaos drill will NOT run",
                file=sys.stderr,
            )
    rows = []
    baseline_per_chip = None
    try:
        for mesh in meshes:
            n = mesh[0] * mesh[1] * mesh[2]
            if n > ndev_avail:
                print(
                    f"weak_scaling: rung {mesh} needs {n} devices, have "
                    f"{ndev_avail}; skipping", file=sys.stderr,
                )
                continue
            grid = tuple(args.local * m for m in mesh)
            cfg = SolverConfig(
                grid=GridConfig(shape=grid),
                stencil=StencilConfig(kind=args.stencil),
                mesh=MeshConfig(shape=mesh),
                precision=(
                    Precision.bf16() if args.dtype == "bf16"
                    else Precision.fp32()
                ),
                backend="jnp",
                time_blocking=args.time_blocking,
            )
            rate, share = run_rung(cfg, args.steps)
            per_chip = rate / n
            if baseline_per_chip is None:
                baseline_per_chip = per_chip
            row = {
                "bench": "weak_scaling",
                "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "platform": platform,
                "mesh_shape": list(mesh),
                "devices": n,
                "grid": list(grid),
                "local_grid": [args.local] * 3,
                "stencil": args.stencil,
                "dtype": cfg.precision.storage,
                "time_blocking": args.time_blocking,
                "steps": args.steps,
                "gcell_per_sec": round(rate, 6),
                "gcell_per_sec_per_chip": round(per_chip, 6),
                "halo_share_model": (
                    None if share is None else round(share, 6)
                ),
                "weak_efficiency": round(per_chip / baseline_per_chip, 4),
                "post_heal": False,
            }
            rows.append(row)
            print(json.dumps(row), flush=True)

            if chaos and mesh == chaos_target:
                keep, at_frac = chaos
                import tempfile

                root = args.ckpt_root or tempfile.mkdtemp(
                    prefix="heat3d_chaos_"
                )
                result, recovery_s, restitch_s, degraded_rate = (
                    run_chaos_rung(cfg, args.steps, keep, at_frac, root)
                )
                dmesh = result.mesh_shape or (keep, 1, 1)
                dn = dmesh[0] * dmesh[1] * dmesh[2]
                row = {
                    "bench": "weak_scaling",
                    "ts": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                    ),
                    "platform": platform,
                    "mesh_shape": list(dmesh),
                    "devices": dn,
                    "grid": list(grid),
                    "local_grid": [args.local] * 3,
                    "stencil": args.stencil,
                    "dtype": cfg.precision.storage,
                    "time_blocking": args.time_blocking,
                    "steps": args.steps,
                    "gcell_per_sec": round(degraded_rate, 6),
                    "gcell_per_sec_per_chip": round(degraded_rate / dn, 6),
                    "halo_share_model": None,
                    "post_heal": True,
                    "injected_mesh": list(mesh),
                    "survivors": keep,
                    "recovery_s": round(recovery_s, 6),
                    "restitch_s": round(restitch_s, 6),
                    "refactors": result.refactors,
                    "degraded_of_baseline": round(
                        (degraded_rate / dn) / baseline_per_chip, 4
                    ),
                }
                rows.append(row)
                print(json.dumps(row), flush=True)
    except BaseException as e:
        obs.deactivate(rc=1, error=f"{type(e).__name__}: {str(e)[:200]}")
        raise

    with open(args.out, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(
        f"weak_scaling: {len(rows)} row(s) -> {args.out}", file=sys.stderr
    )
    obs.deactivate(rc=0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
