#!/usr/bin/env python
"""Provenance lint for bench result records — thin wrapper over the
promoted data-lint core (heat3d_tpu.analysis.provenance), keeping the
established flags; the analysis subsystem owns the rules and shares its
finding/report format with ``heat3d lint`` (docs/ANALYSIS.md).

Round 5 shipped 21 live on-chip rows whose ``ts`` field was null — the
timestamp stamping landed AFTER the healthy window that measured the rows
it was built to provenance (VERDICT.md weak item 2). This lint makes that
class of gap loud at measurement time instead of at judging time: it FAILS
(rc 1) when any row in a results file

- has ``ts`` missing, null, or empty (a row that cannot prove which
  session measured it), or
- is a throughput row missing its route-provenance fields (``platform``,
  ``direct_path``, ``mehrstellen_route``, ``fused_dma_path``,
  ``fused_dma_emulated``, ``streamk_path``, ``streamk_emulated``,
  ``chain_ops`` — ``chain_ops: null`` is legal only for ``backend:
  conv``, where a tap-chain op count does not exist), or
- is a ``time_blocking > 1`` throughput row missing a numeric
  ``cost_redundant_flops_frac`` (deep-tb recompute honesty), or
- is a halo row missing ``platform``, or
- is a ``weak_scaling`` row missing ``platform``, the judged
  ``gcell_per_sec_per_chip``, or its ``post_heal`` elastic provenance, or
- is a ``soak`` row (serve/loadgen.py verdict rows) missing
  ``platform``/``duration_s``/``seed``, violating the conservation law
  ``admitted + shed == submitted``, or missing the judged
  ``sustained_member_gcell_per_s``, ``degraded_s`` chaos provenance, or
  the ``slo`` verdict that judged it, or
- is a throughput/halo row missing a numeric ``sync_rtt_s``.

Wired into the bench report path (scripts/run_bench_suite.sh runs it after
regenerating BASELINE.md, and its rc is the suite's rc), so a session
cannot complete "green" while writing unprovenanced rows. APPEND-mode
sessions scope the lint with ``--start-line N`` to the rows THEY wrote —
a bare run over a whole legacy file still fails on legacy rows by design:
the fix is re-landing the suite in a healthy window, not weakening the
lint.

Usage: scripts/check_provenance.py [--start-line N] RESULTS.jsonl [...]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heat3d_tpu.analysis.provenance import (  # noqa: E402,F401
    MAX_REPORT,
    ROUTE_FIELDS,
    check_file,
    check_row,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
