#!/usr/bin/env python
"""Provenance lint for bench result records.

Round 5 shipped 21 live on-chip rows whose ``ts`` field was null — the
timestamp stamping landed AFTER the healthy window that measured the rows
it was built to provenance (VERDICT.md weak item 2). This lint makes that
class of gap loud at measurement time instead of at judging time: it FAILS
(rc 1) when any row in a results file

- has ``ts`` missing, null, or empty (a row that cannot prove which
  session measured it), or
- is a throughput row missing its route-provenance fields (``platform``,
  ``direct_path``, ``mehrstellen_route``, ``fused_dma_path``,
  ``fused_dma_emulated``, ``chain_ops`` — ``chain_ops: null`` is legal
  only for ``backend: conv``, where a tap-chain op count does not exist), or
- is a halo row missing ``platform``, or
- is a bench row (either kind) missing a numeric ``sync_rtt_s`` — the
  measured host round trip stamped by the harness (cached per backend in
  utils.timing.sync_overhead); without it an ``rtt_dominated`` sample
  cannot be audited from the row alone. A sweep JOURNAL recorded before
  this field existed re-emits its rows verbatim on resume (byte-identical
  replay is the journal's contract), so those replays fail too — by
  design, same as legacy ``ts`` rows: re-land them in a healthy window or
  start a fresh journal; do not weaken the lint.

Wired into the bench report path (scripts/run_bench_suite.sh runs it after
regenerating BASELINE.md, and its rc is the suite's rc), so a session
cannot complete "green" while writing unprovenanced rows. APPEND-mode
sessions scope the lint with ``--start-line N`` to the rows THEY wrote —
otherwise the committed legacy record (15 pre-``ts`` rows) would keep
every resumed session permanently red and the gate would stop meaning
anything. A bare run over the whole file still fails on legacy rows by
design — the fix is re-landing the suite in a healthy window, not
weakening the lint.

Usage: scripts/check_provenance.py [--start-line N] RESULTS.jsonl [...]
"""

from __future__ import annotations

import json
import sys

ROUTE_FIELDS = (
    "platform",
    "direct_path",
    "mehrstellen_route",
    "fused_dma_path",
    "fused_dma_emulated",
    "streamk_path",
    "streamk_emulated",
)
MAX_REPORT = 20


def check_row(r: dict) -> list:
    problems = []
    ts = r.get("ts")
    if not (isinstance(ts, str) and ts):
        problems.append(
            "ts missing/null (row cannot prove its measurement session)"
        )
    if r.get("bench") == "throughput":
        for f in ROUTE_FIELDS:
            if f not in r:
                problems.append(f"missing route-provenance field {f!r}")
        if "chain_ops" not in r:
            problems.append("missing route-provenance field 'chain_ops'")
        elif r["chain_ops"] is None and r.get("backend") != "conv":
            problems.append(
                "chain_ops is null on a non-conv row (op-count provenance "
                "lost)"
            )
        # temporally-blocked rows execute redundant ghost-ring recompute;
        # without the recorded fraction their Gcell/s cannot be discounted
        # to useful work at judging time (deep-tb honesty — a tb=4 "win"
        # must carry its own recompute tax on the row)
        tb = r.get("time_blocking", 1)
        if isinstance(tb, int) and tb > 1 and not isinstance(
            r.get("cost_redundant_flops_frac"), (int, float)
        ):
            problems.append(
                "cost_redundant_flops_frac missing/non-numeric on a "
                f"time_blocking={tb} row (redundant-compute provenance "
                "lost)"
            )
    elif r.get("bench") == "halo":
        if "platform" not in r:
            problems.append("missing 'platform'")
    if r.get("bench") in ("throughput", "halo") and not isinstance(
        r.get("sync_rtt_s"), (int, float)
    ):
        problems.append(
            "sync_rtt_s missing/non-numeric (RTT-dominated samples not "
            "auditable from the row)"
        )
    return problems


def check_file(path: str, start_line: int = 1) -> list:
    """(line_no, description) for every defect in ``path`` at or after
    ``start_line`` (1-based; earlier lines belong to a prior session)."""
    bad = []
    try:
        f = open(path)
    except OSError as e:
        return [(0, f"cannot open {path}: {e}")]
    with f:
        for i, line in enumerate(f, start=1):
            if i < start_line:
                continue
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                bad.append((i, "unparseable JSON"))
                continue
            if not isinstance(r, dict) or r.get("bench") not in (
                "throughput",
                "halo",
            ):
                continue  # foreign lines (headline records, notes) pass
            for p in check_row(r):
                bad.append((i, p))
    return bad


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    start_line = 1
    if argv and argv[0] == "--start-line":
        if len(argv) < 2:
            print("--start-line needs a value", file=sys.stderr)
            return 2
        start_line = int(argv[1])
        argv = argv[2:]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    failed = False
    for path in argv:
        bad = check_file(path, start_line)
        if not bad:
            print(f"provenance ok: {path}")
            continue
        failed = True
        print(
            f"provenance FAIL: {path}: {len(bad)} defect(s)", file=sys.stderr
        )
        for line_no, desc in bad[:MAX_REPORT]:
            print(f"  {path}:{line_no}: {desc}", file=sys.stderr)
        if len(bad) > MAX_REPORT:
            print(f"  ... and {len(bad) - MAX_REPORT} more", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
