#!/usr/bin/env bash
# Persistent measurement driver: keep resuming the one-shot measurement
# session (APPEND mode) until the suite record is complete or the attempt
# budget runs out. Survives long axon-pool outages: each attempt's initial
# probe gate waits up to TPU_WAIT for the chip, the suite probe-gates every
# row, and APPEND=1 means an interrupted attempt never re-spends budget on
# rows already landed (see scripts/tpu_measure_all.sh and the claim-expiry
# notes in heat3d_tpu/utils/backendprobe.py).
#
# Usage: scripts/measure_until_complete.sh [attempts]
# Env: TPU_WAIT (per-gate wait, default 3300 s), ROW_TIMEOUT (default
# 1500 s), MIN_ROWS / MIN_HALOS (completion thresholds; defaults cover the
# single-chip suite minus optional rows).
set -uo pipefail
cd "$(dirname "$0")/.."

ATTEMPTS=${1:-10}
for i in $(seq 1 "$ATTEMPTS"); do
  echo "=== measurement attempt $i/$ATTEMPTS $(date -u +%FT%TZ) ==="
  APPEND=1 TPU_WAIT="${TPU_WAIT:-3300}" ROW_TIMEOUT="${ROW_TIMEOUT:-1500}" \
    bash scripts/tpu_measure_all.sh
  # grep -c prints nothing (not 0) when the file is missing — default so
  # the -ge tests below stay integer comparisons on a fresh record
  rows=$(grep -c '"bench": "throughput"' bench_results.jsonl 2>/dev/null || true)
  halos=$(grep -c '"bench": "halo"' bench_results.jsonl 2>/dev/null || true)
  rows=${rows:-0}
  halos=${halos:-0}
  echo "=== attempt $i done: $rows throughput + $halos halo rows ==="
  if [ "$rows" -ge "${MIN_ROWS:-15}" ] && [ "$halos" -ge "${MIN_HALOS:-6}" ]; then
    echo "suite complete"
    exit 0
  fi
  # Pacing between attempts routes through the shared RetryPolicy (jittered
  # backoff, capped) instead of a bare sleep — the per-gate waiting inside
  # each attempt already goes through it via backendprobe --wait. No sleep
  # after the LAST attempt: nothing follows it but the failure exit.
  [ "$i" -lt "$ATTEMPTS" ] && python heat3d_tpu/resilience/retry.py \
    --attempt "$i" \
    --base "${ATTEMPT_BACKOFF:-60}" --cap "${ATTEMPT_BACKOFF_CAP:-300}" \
    --seed-extra "$(hostname)" --sleep
done
echo "attempt budget exhausted with $rows/$halos rows" >&2
exit 1
