#!/usr/bin/env bash
# One-shot TPU measurement session (run when the axon tunnel is healthy):
#   1. <2-min smoke tier (compiled kernels sane on chip)
#   2. headline bench.py JSON line (judged config, best settings — FIRST,
#      so a short healthy window lands the judged metric before anything)
#   3. benchmark suite -> bench_results.jsonl + BASELINE.md measured tables
#   4. A/B stages + profile traces + ab_decide decisions
#
# Everything appends to $LOG so a wedged tunnel mid-run still leaves the
# completed stages' records on disk.
set -uo pipefail
cd "$(dirname "$0")/.."

LOG="${LOG:-tpu_measure.log}"
echo "=== tpu_measure_all $(date -u +%FT%TZ) ===" | tee -a "$LOG"

# One chip-claim at a time: gate every stage on a killable probe loop so a
# stale pool claim (left by any client killed mid-claim) costs bounded
# waiting, not a stage timeout burned inside backend init. See
# heat3d_tpu/utils/backendprobe.py::wait_for_backend.
# Anchor-then-short gating, shared by EVERY gate in this session (the
# suite script implements the same rule with the same knob): the first
# failure pays the full TPU_WAIT (the wait-for-heal anchor); while the
# tunnel stays down, later gates wait only TPU_WAIT_SHORT (default
# 300 s). Gates run back-to-back, so a heal is still detected within one
# probe interval either way — short gates just cycle through dead
# stages/arms faster, and the driver loop (measure_until_complete.sh)
# retries what was skipped next attempt. A success re-arms the full
# anchor: a NEW outage gets a new full wait.
# Per-row sweep state (resilience.sweepstate JSONL journal): an
# interrupted session RESUMES AT THE FIRST MISSING ROW instead of
# re-running landed ones — a 30-minute healthy window after an outage
# spends itself on the missing A/B arms (historically stages 3b-3g),
# not on re-measuring the headline. Rows are marked only when a real
# JSON result line landed. Delete $SWEEP_STATE to force a fresh full
# session (a new round's record should not ride on last round's rows).
SWEEP_STATE="${SWEEP_STATE:-tpu_measure_state.jsonl}"
row_done() {
  # direct file invocation: sweepstate is pure stdlib, -m would pay a
  # multi-second package (jax) import per gated row
  python heat3d_tpu/resilience/sweepstate.py done "$SWEEP_STATE" "$1" 2>/dev/null
}
row_mark() {
  python heat3d_tpu/resilience/sweepstate.py mark "$SWEEP_STATE" "$1" \
    || echo "warn: could not mark sweep row $1" | tee -a "$LOG"
}
# row_landed OUT: true iff OUT is a JSON row measured ON CHIP — bench rows
# and CLI summaries both carry "platform" (a child that silently fell back
# to CPU still prints JSON; retiring its row would freeze a CPU number
# into the A/B record forever)
row_landed() {
  [[ $1 == \{* && $1 == *'"platform": "tpu"'* ]]
}

GATE_FAILED=""
wait_tpu() {
  local w="${TPU_WAIT:-1800}"
  [[ -n $GATE_FAILED ]] && w="${TPU_WAIT_SHORT:-300}"
  if python -m heat3d_tpu.utils.backendprobe \
      --wait "$w" --interval "${TPU_WAIT_INTERVAL:-60}" >/dev/null 2>&1; then
    GATE_FAILED=""
    return 0
  fi
  GATE_FAILED=1
  echo "TPU unreachable past ${w}s; skipping: $*" | tee -a "$LOG"
  return 1
}
# a TPU measurement session is meaningless off the axon env — fail fast
# rather than waiting TPU_WAIT for a platform that can't appear
if [[ -z "${PALLAS_AXON_POOL_IPS:-}" || "${JAX_PLATFORMS:-axon}" == cpu ]]; then
  echo "not an axon TPU env (PALLAS_AXON_POOL_IPS unset or cpu forced) — aborting" \
    | tee -a "$LOG"
  exit 1
fi
if ! wait_tpu "initial probe"; then
  echo "TPU never answered — aborting" | tee -a "$LOG"
  exit 1
fi

echo "--- stage 1: smoke tier" | tee -a "$LOG"
timeout -k 30 900 python -m pytest tests/ -m tpu_smoke -q 2>&1 | tail -3 | tee -a "$LOG"

# The headline comes BEFORE the full suite: if the healthy window is
# short, the judged metric's own line must land first, not after two
# hours of 256^3 rows.
echo "--- stage 2: headline bench" | tee -a "$LOG"
# outer timeout > bench.py's internal deadline (default 1500 s, which now
# includes up to ~900 s of claim-outlasting probes) so the JSON line always
# lands before SIGKILL
if row_done "2:headline"; then
  echo "headline: already landed this session (state)" | tee -a "$LOG"
elif wait_tpu "headline bench"; then
  # stderr goes to $LOG only: a trailing jax/absl shutdown warning on
  # stderr must not displace the JSON line from tail -1 (the row would
  # then never be marked done and every attempt re-runs the headline)
  out=$(timeout -k 30 1800 python bench.py 2>>"$LOG" | tee -a "$LOG" | tail -1)
  # only a LIVE headline line retires the row: a CPU-fallback line keeps
  # it pending so the next healthy window re-lands the judged metric
  [[ $out != *'"error"'* ]] && row_landed "$out" && row_mark "2:headline"
fi

echo "--- stage 0b: new-kernel probes (bounded; a kernel FAILURE flips its route off)" | tee -a "$LOG"
# Kernels added since the last real-chip session get one tiny-grid
# compile+execute each BEFORE the long suite, so a Mosaic lowering
# surprise costs one bounded probe (PROBE_TIMEOUT, default 300 s) and
# disables just its route — not a stage timeout mid-session (VERDICT r3
# #6). Only a real execution failure disables a route: an unreachable
# tunnel leaves it enabled (unvetted), since every A/B iteration gates on
# its own wait_tpu anyway. Pre-set SKIP_* env flags skip the probe too.
probe_kernel() {  # probe_kernel NAME CMD... -> 0 ok/inconclusive, 1 kernel failed
  local name="$1" rc; shift
  wait_tpu "probe $name" || {
    echo "probe $name: tunnel unreachable — route stays enabled, unvetted" \
      | tee -a "$LOG"
    return 0
  }
  # probe output goes to a side log: a route-disabling Mosaic error must
  # leave its traceback in the session artifacts, not just an exit code
  echo "--- probe $name $(date -u +%FT%TZ)" >> "$LOG.probes"
  timeout -k 15 "${PROBE_TIMEOUT:-300}" "$@" >>"$LOG.probes" 2>&1
  rc=$?
  if [[ $rc -eq 0 ]]; then
    echo "probe $name: ok" | tee -a "$LOG"
    return 0
  fi
  if [[ $rc -eq 124 || $rc -eq 137 ]]; then
    # Timeout: either the tunnel died under the probe (inconclusive) or
    # the kernel itself deadlocked (a real verdict — letting it through
    # would hang every suite row that uses it). A quick re-probe of the
    # backend distinguishes them: still reachable means the hang was the
    # kernel's.
    if python -m heat3d_tpu.utils.backendprobe --wait 120 --interval 20 \
        >/dev/null 2>&1; then
      echo "probe $name: HUNG (rc=$rc) with the tunnel healthy — kernel deadlock, route disabled" \
        | tee -a "$LOG"
      return 1
    fi
    echo "probe $name: timed out (rc=$rc) with the tunnel down — inconclusive, route stays enabled" \
      | tee -a "$LOG"
    return 0
  fi
  echo "probe $name: FAILED (rc=$rc, traceback in $LOG.probes) — route disabled for this session" \
    | tee -a "$LOG"
  return 1
}
SKIP_FY_AB=${SKIP_FY_AB:-}; SKIP_MEHRSTELLEN=${SKIP_MEHRSTELLEN:-}
[[ -z $SKIP_FY_AB ]] && { probe_kernel "27pt-yfactored" \
    python -m heat3d_tpu.cli --grid 64 --stencil 27pt --steps 3 \
    --golden-check \
  || { export HEAT3D_FACTOR_Y=0; SKIP_FY_AB=1; }; }
[[ -z $SKIP_MEHRSTELLEN ]] && { probe_kernel "mehrstellen-tb1" \
    env HEAT3D_MEHRSTELLEN=1 python -m heat3d_tpu.cli --grid 64 \
    --stencil 27pt --steps 3 \
  || SKIP_MEHRSTELLEN=1; }
[[ -z $SKIP_MEHRSTELLEN ]] && { probe_kernel "mehrstellen-tb2" \
    env HEAT3D_MEHRSTELLEN=1 python -m heat3d_tpu.cli --grid 64 \
    --stencil 27pt --steps 3 --time-blocking 2 \
  || SKIP_MEHRSTELLEN=1; }
# halo-dma probe failure flips the route off for the rest of the session:
# SKIP_HALO_DMA gates any later dma-transport stage here, and the marker
# line in $LOG is what a pod operator checks before pod_ab_fused.sh
# (docs/POD_RUNBOOK.md §3 orders the control arm first for this reason).
SKIP_HALO_DMA=${SKIP_HALO_DMA:-}
[[ -z $SKIP_HALO_DMA ]] && { probe_kernel "halo-dma-w1" \
    python -m heat3d_tpu.cli --grid 64 --halo dma --steps 3 \
  || { SKIP_HALO_DMA=1
       echo "route-disabled: halo=dma (probe failed)" | tee -a "$LOG"; }; }
[[ -z ${SKIP_BF16_COMPUTE:-} ]] && { probe_kernel "bf16-compute-tb2" \
    python -m heat3d_tpu.cli --grid 64 --dtype bf16 --compute-dtype bf16 \
    --time-blocking 2 --steps 3 \
  || export SKIP_BF16_COMPUTE=1; }
# Fused DMA-overlap probes (the route pod_ab_fused.sh measures): need an
# x-slab mesh of >= 2 chips — probed here ONLY on a multi-chip host so a
# Mosaic surprise in the fused kernels surfaces as one bounded probe, not
# mid-A/B. Single-chip sessions leave them unvetted by construction. The
# device-count probe itself takes a chip claim, so it only runs when its
# result can matter (no SKIP flag already set).
SKIP_FUSED_DMA=${SKIP_FUSED_DMA:-}
if [[ -z $SKIP_HALO_DMA && -z $SKIP_FUSED_DMA ]]; then
  # empty NCHIPS = probe unreachable (distinct from a genuine count; the
  # routes then stay enabled, unvetted — probe_kernel's own contract)
  NCHIPS=$(python - <<'EOF'
from heat3d_tpu.utils.backendprobe import probe_device_count
n = probe_device_count()
print("" if n is None else n)
EOF
)
  if [[ -z $NCHIPS ]]; then
    echo "fused-dma probes: tunnel unreachable for device count — routes stay enabled, unvetted" \
      | tee -a "$LOG"
  elif [[ $NCHIPS -lt 2 ]]; then
    echo "fused-dma probes skipped: $NCHIPS chip(s) — route needs an x-slab mesh" \
      | tee -a "$LOG"
  else
    # grid scales with the slab so local nx = 8 >= the kernels' gates
    # (tb=1 needs nx >= 2, tb=2 nx >= 4) — a fixed grid would leave the
    # probe vacuous (non-fused fallback route "ok") on larger slices
    FUSED_GRID=$((8 * NCHIPS))
    probe_kernel "fused-dma-tb1" \
        python -m heat3d_tpu.cli --grid "$FUSED_GRID" --mesh "$NCHIPS" 1 1 \
        --halo dma --overlap --steps 3 \
      || { SKIP_FUSED_DMA=1
           echo "route-disabled: fused-dma tb=1 (probe failed)" | tee -a "$LOG"; }
    [[ -z $SKIP_FUSED_DMA ]] && { probe_kernel "fused-dma-tb2" \
        python -m heat3d_tpu.cli --grid "$FUSED_GRID" --mesh "$NCHIPS" 1 1 \
        --halo dma --overlap --time-blocking 2 --steps 4 \
      || { SKIP_FUSED_DMA=1
           echo "route-disabled: fused-dma tb=2 (probe failed)" | tee -a "$LOG"; }; }
  fi
fi

echo "--- stage 3: bench suite" | tee -a "$LOG"
# The suite probe-gates each row internally; its stderr log (suite: ...
# skip/fail lines + row tracebacks) is bench_results.err.log.
timeout -k 30 "${SUITE_TIMEOUT:-7200}" bash scripts/run_bench_suite.sh \
  bench_results.jsonl 2>&1 | tail -3 | tee -a "$LOG"

# Stages 3b-3f ride the TUNER (ROADMAP carry-over, retired this PR):
# each A/B is one `tune run --no-cache-write --json` invocation. The
# trial table IS the A/B record — per-trial tune_trial ledger events,
# full bench-row provenance (sync_rtt_s, rtt_dominated exclusion), and
# the JSON `decisions` field carries the per-knob pairwise verdicts, so
# ab_decide's log scraping is no longer needed for these stages.
# --no-cache-write: a measurement session records evidence; flipping the
# operator cache stays an explicit `tune run` (no --no-cache-write) or
# `tune apply`. Env-knob arms (HEAT3D_FACTOR_Y / HEAT3D_MEHRSTELLEN /
# HEAT3D_FACTOR_7PT) wrap the invocation: the tuner searches the config
# knobs, the env prefix selects the code-path arm, and the A/B across
# arms is the two JSON lines' winners side by side in $LOG.
tune_ab() {  # tune_ab KEY DESC [VAR=V ...] -- TUNE_RUN_ARGS...
  local key="$1" desc="$2"; shift 2
  local envp=()
  while [[ $# -gt 0 && $1 != "--" ]]; do envp+=("$1"); shift; done
  shift  # the --
  row_done "$key" && { echo "$desc: already landed (state)" | tee -a "$LOG"; return 0; }
  wait_tpu "$desc" || return 1
  local out
  out=$(env ${envp[@]+"${envp[@]}"} timeout -k 30 "${TUNE_AB_TIMEOUT:-1800}" \
    python -m heat3d_tpu.cli tune run --no-cache-write --json \
    --steps 50 --repeats 2 "$@" 2>>"$LOG" | tail -1)
  echo "$desc: $out" | tee -a "$LOG"
  row_landed "$out" && row_mark "$key"
}

echo "--- stage 3b: route A/B via tuner (512^3 fp32 tb=1: auto/pallas/jnp/conv + exchange arm)" | tee -a "$LOG"
# conv = one XLA conv_general_dilated (MXU) — the obvious XLA-native
# implementation, measured so the kernels' advantage is a committed number
tune_ab "3b:routes" "route A/B" -- \
  --grid 512 --mesh 1 1 1 --knob backend=pallas,jnp,conv
# the exchange arm: HEAT3D_NO_DIRECT=1 disables the direct kernel routes,
# so backend=pallas here measures the exchange-path streaming kernel —
# the old stage's direct-vs-exchange comparison, kept as its own row
tune_ab "3b:exchange" "route A/B (exchange arm)" HEAT3D_NO_DIRECT=1 -- \
  --grid 512 --mesh 1 1 1 --knob backend=pallas,jnp

# The factored-default 27pt and bf16-compute rows are already in the
# suite record (stage 3); these A/B stages log the counterfactual sides.
echo "--- stage 3c: 27pt y-factoring A/B via tuner (512^3 fp32, tb searched)" | tee -a "$LOG"
[[ -n $SKIP_FY_AB ]] && echo "skipped: y-factored probe failed" | tee -a "$LOG"
for fy in $([[ -z $SKIP_FY_AB ]] && echo 1 0); do
  tune_ab "3c:fy=$fy" "factor_y=$fy" HEAT3D_FACTOR_Y=$fy -- \
    --grid 512 --stencil 27pt --mesh 1 1 1 --knob time_blocking=1,2
done

echo "--- stage 3d: bf16-compute A/B via tuner (1024^3, tb 1 vs 2)" | tee -a "$LOG"
# storage/compute grid: bf16/fp32 vs bf16/bf16 answers whether the bf16
# tb=2 ceiling gap is VPU-width-bound; fp32/bf16 runs the same width A/B
# on the fp32 traffic shape (accuracy gates: tests/test_solver.py bf16
# tiers). fp32/fp32 is the committed headline row (suite stage 3).
bf16_modes=("bf16 fp32" "bf16 bf16" "fp32 bf16")
[[ -n ${SKIP_BF16_COMPUTE:-} ]] && { bf16_modes=()
  echo "skipped: bf16-compute probe failed" | tee -a "$LOG"; }
for dt in ${bf16_modes[@]+"${bf16_modes[@]}"}; do
  read -r st cd <<<"$dt"
  tune_ab "3d:$st/$cd" "storage=$st compute=$cd" -- \
    --grid 1024 --dtype $st --compute-dtype $cd --mesh 1 1 1 \
    --knob time_blocking=2
done

echo "--- stage 3e: 27pt mehrstellen A/B via tuner (512^3 fp32, tb searched)" | tee -a "$LOG"
# separable S+F route (q-ring direct kernels) vs the factored tap chain;
# chain_ops/mehrstellen_route in each trial row pin which route ran
[[ -n $SKIP_MEHRSTELLEN ]] && echo "skipped: mehrstellen probe failed" | tee -a "$LOG"
for mh in $([[ -z $SKIP_MEHRSTELLEN ]] && echo 0 1); do
  tune_ab "3e:mh=$mh" "mehrstellen=$mh" HEAT3D_MEHRSTELLEN=$mh -- \
    --grid 512 --stencil 27pt --mesh 1 1 1 --knob time_blocking=1,2
done

echo "--- stage 3f: 7pt x-factoring A/B via tuner (1024^3 fp32 tb=2 — the headline)" | tee -a "$LOG"
# HEAT3D_FACTOR_7PT=1 trades the headline chain's two x-shifted plane
# reads for one unshifted add on the plane sum; if it wins, the headline
# default flips next session (the committed record runs factor=0)
for f7 in 0 1; do
  tune_ab "3f:f7=$f7" "factor_7pt=$f7" HEAT3D_FACTOR_7PT=$f7 -- \
    --grid 1024 --mesh 1 1 1 --knob time_blocking=2
done

echo "--- stage 3g: K-cadence convergence A/B (512^3 tb=2, 400 capped steps)" | tee -a "$LOG"
# NOT a tuner invocation: residual-sync cadence is a converge-loop
# behavior (the tuner's metric is bench throughput, which never syncs
# mid-loop) — the A/B must drive the real `heat3d` stepping loop.
# Measures what residual-sync cadence costs (SURVEY §3.3: syncing every
# step serializes the pipeline): identical 400-step converge runs under an
# unreachable tol, checking every step vs every 9 (K-1 = 8 updates = 4
# clean tb=2 supersteps between checks — a multiple of the blocking
# factor, so the delta measures cadence, not remainder-step overhead).
# Recorded where --residual-every is documented (VERDICT r3 #8).
for re in 1 9; do
  row_done "3g:re=$re" && { echo "residual_every=$re: already landed (state)" | tee -a "$LOG"; continue; }
  wait_tpu "K-cadence A/B re=$re" || continue
  out=$(timeout -k 30 1200 python -m heat3d_tpu.cli --grid 512 --tol 1e-12 \
    --steps 400 --residual-every $re --time-blocking 2 --init gaussian \
    2>/dev/null | tail -1)
  echo "residual_every=$re: $out" | tee -a "$LOG"
  row_landed "$out" && row_mark "3g:re=$re"
done

echo "--- stage 4: profile traces" | tee -a "$LOG"
profile_row() {  # profile_row KEY OUTDIR ENVVARS...
  local key="$1" outdir="$2" out; shift 2
  row_done "4:$key" && { echo "profile $key: already landed (state)" | tee -a "$LOG"; return 0; }
  wait_tpu "profile $key" || return 1
  out=$(env "$@" timeout -k 30 1200 \
    bash scripts/profile_bench.sh "$outdir" 2>&1 | tee -a "$LOG")
  # retire the row only when the embedded bench row proves the trace ran
  # ON CHIP (profile_bench prints it) — an exit-0 run whose jax silently
  # fell back to CPU must stay pending, like every other stage
  [[ $out == *'"platform": "tpu"'* ]] && row_mark "4:$key"
}
profile_row tb1 /tmp/heat3d_profile_tb1 GRID=512 STEPS=20 TB=1
profile_row tb2 /tmp/heat3d_profile_tb2 GRID=512 STEPS=20 TB=2
# 27pt VPU-bound claim: capture the op mix at the ceiling (VERDICT r2 #4)
profile_row 27pt /tmp/heat3d_profile_27pt GRID=512 STEPS=20 TB=1 STENCIL=27pt
# bf16 tb=2 ceiling question (32-43% of traffic ceiling): the trace shows
# whether the fused sweep's extra time is VPU ops or VMEM plane assembly
profile_row bf16_tb2 /tmp/heat3d_profile_bf16_tb2 GRID=512 STEPS=20 TB=2 DTYPE=bf16

# halo p50 rows (device-side k-exchange loop) come from stage 3's suite:
# one row per (grid, dtype) exchange shape, labeled local-only on the
# single-chip mesh — the ICI numbers need a pod slice.

echo "--- stage 5: A/B decisions (scripts/ab_decide.py)" | tee -a "$LOG"
python scripts/ab_decide.py "$LOG" 2>&1 | tee -a "$LOG" || true

echo "=== done $(date -u +%FT%TZ) ===" | tee -a "$LOG"
