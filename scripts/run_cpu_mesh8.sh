#!/usr/bin/env bash
# Run any heat3d command on a simulated 8-device CPU mesh — the moral
# equivalent of the reference's `mpirun -np 8` single-node oversubscription
# test (SURVEY.md §4). Extra args pass through to `python -m heat3d_tpu`.
#
# Usage: scripts/run_cpu_mesh8.sh --grid 64 --steps 10 --mesh 2 2 2 --golden-check
set -euo pipefail
cd "$(dirname "$0")/.."

exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
  python -m heat3d_tpu "$@"
