"""Summarize a jax.profiler trace directory: per-op device time.

Reads the xplane protobuf the profiler writes and prints the top device ops
by total self time — enough to attribute a roofline gap (DMA wait vs
compute vs dispatch gaps) without shipping the trace to TensorBoard.
"""

from __future__ import annotations

import glob
import os
import sys
from collections import defaultdict


def find_xplane(logdir: str):
    pats = os.path.join(logdir, "**", "*.xplane.pb")
    files = sorted(glob.glob(pats, recursive=True))
    return files[-1] if files else None


def summarize(path: str) -> int:
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore
    except ImportError:
        # soft fallback: the capture itself succeeded, so don't fail the
        # calling script — just point at the trace
        print(
            "no xplane_pb2 available; open the trace in TensorBoard "
            f"(tensorboard --logdir {os.path.dirname(path)})"
        )
        return 0
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    planes = [
        p
        for p in xs.planes
        if "TPU" in p.name or "/device" in p.name.lower()
    ]
    if not planes:  # CPU-only trace: fall back to the host plane
        planes = [p for p in xs.planes if p.lines]
    for plane in planes:
        # A device plane carries several lines covering the SAME wall time
        # (XLA Modules / XLA Ops / Steps); summing across them would double-
        # count. Aggregate one line only: the op-level line if present, else
        # the busiest line.
        def line_us(line):
            return sum(ev.duration_ps for ev in line.events) / 1e6

        lines = [ln for ln in plane.lines if ln.events]
        if not lines:
            continue
        ops = [ln for ln in lines if "op" in ln.name.lower()]
        line = ops[0] if ops else max(lines, key=line_us)
        totals = defaultdict(float)
        counts = defaultdict(int)
        for ev in line.events:
            meta = plane.event_metadata[ev.metadata_id]
            totals[meta.name] += ev.duration_ps / 1e6
            counts[meta.name] += 1
        print(
            f"\n== {plane.name} [line: {line.name or '?'}] "
            f"(total {sum(totals.values())/1e3:.2f} ms)"
        )
        for name, us in sorted(totals.items(), key=lambda kv: -kv[1])[:25]:
            print(f"  {us/1e3:9.3f} ms  x{counts[name]:<6} {name[:90]}")
    return 0


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    path = sys.argv[1]
    if os.path.isdir(path):
        xp = find_xplane(path)
        if xp is None:
            print(f"no .xplane.pb under {path}")
            return 1
        path = xp
    print(f"trace: {path}")
    return summarize(path)


if __name__ == "__main__":
    sys.exit(main())
