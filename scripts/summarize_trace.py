"""Summarize a jax.profiler trace directory: per-op device time.

Reads the xplane protobuf the profiler writes and prints the top device ops
by total self time — enough to attribute a roofline gap (DMA wait vs
compute vs dispatch gaps) without shipping the trace to TensorBoard.
"""

from __future__ import annotations

import glob
import os
import sys
from collections import defaultdict


def find_xplane(logdir: str):
    pats = os.path.join(logdir, "**", "*.xplane.pb")
    files = sorted(glob.glob(pats, recursive=True))
    return files[-1] if files else None


def summarize(path: str) -> int:
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore
    except ImportError:
        print(
            "no xplane_pb2 available; open the trace in TensorBoard "
            f"(tensorboard --logdir {os.path.dirname(path)})"
        )
        return 1
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    planes = [
        p
        for p in xs.planes
        if "TPU" in p.name or "/device" in p.name.lower()
    ]
    if not planes:  # CPU-only trace: fall back to the host plane
        planes = [p for p in xs.planes if p.lines]
    for plane in planes:
        totals = defaultdict(float)
        counts = defaultdict(int)
        for line in plane.lines:
            for ev in line.events:
                meta = plane.event_metadata[ev.metadata_id]
                dur_us = ev.duration_ps / 1e6
                totals[meta.name] += dur_us
                counts[meta.name] += 1
        if not totals:
            continue
        print(f"\n== {plane.name} (total {sum(totals.values())/1e3:.2f} ms)")
        for name, us in sorted(totals.items(), key=lambda kv: -kv[1])[:25]:
            print(f"  {us/1e3:9.3f} ms  x{counts[name]:<6} {name[:90]}")
    return 0


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    path = sys.argv[1]
    if os.path.isdir(path):
        xp = find_xplane(path)
        if xp is None:
            print(f"no .xplane.pb under {path}")
            return 1
        path = xp
    print(f"trace: {path}")
    return summarize(path)


if __name__ == "__main__":
    sys.exit(main())
