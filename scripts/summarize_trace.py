"""Thin wrapper: the trace-summary core now lives in
``heat3d_tpu/obs/perf/timeline.py`` (the ``heat3d obs timeline``
subsystem), promoted there so the xplane parsing, the per-phase device
totals, and the profile→roofline join share one module — the same
promotion pattern as scripts/roofline_check.py and scripts/ab_decide.py.
This script keeps the historical invocation working:

    python scripts/summarize_trace.py TRACE_DIR_OR_XPLANE_PB

Same flag (one positional path), same output: top device ops by total
self time plus the per-heat3d-phase table. The aggregation helpers are
re-exported so existing importers (tests) keep working.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heat3d_tpu.obs.perf.timeline import (  # noqa: E402,F401
    PHASE_RE,
    aggregate_line,
    find_xplane,
    phase_name,
    phase_totals,
    pick_line,
    summarize,
    summarize_plane,
    summarize_trace_main as main,
)

if __name__ == "__main__":
    sys.exit(main())
