"""Summarize a jax.profiler trace directory: per-op and per-PHASE device time.

Reads the xplane protobuf the profiler writes and prints the top device ops
by total self time — enough to attribute a roofline gap (DMA wait vs
compute vs dispatch gaps) without shipping the trace to TensorBoard. Ops
emitted under the solver's ``jax.named_scope`` brackets (``heat3d.stencil``,
``heat3d.halo_exchange``, ``heat3d.fused_dma``, ``heat3d.residual`` — see
heat3d_tpu/obs/trace.py and docs/OBSERVABILITY.md) carry the scope in
their metadata name, so the summary also aggregates device time by OUR
phases instead of raw XLA op names.

The aggregation logic is pure and duck-typed (``pick_line`` /
``aggregate_line`` / ``phase_totals``) so tests drive it with synthetic
plane objects when the ``xplane_pb2`` proto module is absent
(tests/test_obs.py).
"""

from __future__ import annotations

import glob
import os
import re
import sys
from collections import defaultdict

# innermost heat3d phase token in an op/metadata name: named_scope nests
# (heat3d.stencil/heat3d.halo_exchange/...), and the INNERMOST scope is
# the phase that op belongs to — findall + [-1] picks it. The (?!py\b)
# lookahead keeps host-plane PYTHON FRAMES ("$heat3d.py:301 run") from
# masquerading as a phase named "heat3d.py". Dotted sub-phases
# ("heat3d.halo.x") are one token: the continuation admits further
# components unless they open with a digit (XLA's ".N" op suffixes, as in
# "fusion.2", are not phase path components).
PHASE_RE = re.compile(
    r"heat3d\.(?!py\b)[A-Za-z_][A-Za-z0-9_]*"
    r"(?:\.(?!py\b)[A-Za-z_][A-Za-z0-9_]*)*"
)


def find_xplane(logdir: str):
    pats = os.path.join(logdir, "**", "*.xplane.pb")
    files = sorted(glob.glob(pats, recursive=True))
    return files[-1] if files else None


def pick_line(lines):
    """The ONE line to aggregate per plane. A device plane carries several
    lines covering the SAME wall time (XLA Modules / XLA Ops / Steps);
    summing across them would double-count. Pick the op-level line if
    present, else the busiest line. ``lines`` must be pre-filtered to
    non-empty (``ln.events``)."""

    def line_us(line):
        return sum(ev.duration_ps for ev in line.events) / 1e6

    ops = [ln for ln in lines if "op" in ln.name.lower()]
    return ops[0] if ops else max(lines, key=line_us)


def aggregate_line(line, event_metadata):
    """(totals_us, counts) per metadata name for one line's events.
    ``event_metadata`` is the plane's metadata_id -> metadata mapping
    (proto map or plain dict of objects with ``.name``)."""
    totals = defaultdict(float)
    counts = defaultdict(int)
    for ev in line.events:
        meta = event_metadata[ev.metadata_id]
        totals[meta.name] += ev.duration_ps / 1e6
        counts[meta.name] += 1
    return totals, counts


def phase_name(op_name: str):
    """The heat3d phase an op belongs to (its innermost ``heat3d.*`` scope
    token), or None for ops outside any named phase."""
    hits = PHASE_RE.findall(op_name)
    return hits[-1] if hits else None


def phase_totals(totals):
    """Group per-op totals by heat3d phase; unscoped time lands in
    ``(unattributed)``."""
    phases = defaultdict(float)
    for name, us in totals.items():
        phases[phase_name(name) or "(unattributed)"] += us
    return dict(phases)


def summarize_plane(plane, top: int = 25, out=None) -> None:
    out = out or sys.stdout
    lines = [ln for ln in plane.lines if ln.events]
    if not lines:
        return
    line = pick_line(lines)
    totals, counts = aggregate_line(line, plane.event_metadata)
    print(
        f"\n== {plane.name} [line: {line.name or '?'}] "
        f"(total {sum(totals.values())/1e3:.2f} ms)",
        file=out,
    )
    for name, us in sorted(totals.items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {us/1e3:9.3f} ms  x{counts[name]:<6} {name[:90]}", file=out)
    phases = phase_totals(totals)
    # a phase table with ONLY unattributed time is noise (a trace captured
    # without the named scopes); print it when any phase resolved
    if set(phases) - {"(unattributed)"}:
        total_us = sum(phases.values()) or 1.0
        print("  -- by heat3d phase --", file=out)
        for name, us in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(
                f"  {us/1e3:9.3f} ms  {100.0 * us / total_us:5.1f}%  {name}",
                file=out,
            )


def summarize(path: str) -> int:
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore
    except ImportError:
        # soft fallback: the capture itself succeeded, so don't fail the
        # calling script — just point at the trace
        print(
            "no xplane_pb2 available; open the trace in TensorBoard "
            f"(tensorboard --logdir {os.path.dirname(path)})"
        )
        return 0
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    planes = [
        p
        for p in xs.planes
        if "TPU" in p.name or "/device" in p.name.lower()
    ]
    if not planes:  # CPU-only trace: fall back to the host plane
        planes = [p for p in xs.planes if p.lines]
    for plane in planes:
        summarize_plane(plane)
    return 0


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    path = sys.argv[1]
    if os.path.isdir(path):
        xp = find_xplane(path)
        if xp is None:
            print(f"no .xplane.pb under {path}")
            return 1
        path = xp
    print(f"trace: {path}")
    return summarize(path)


if __name__ == "__main__":
    sys.exit(main())
