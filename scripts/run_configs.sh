#!/usr/bin/env bash
# Judged-config launcher — the mpirun-script analogue (SURVEY.md §2 C12).
#
# The reference is launched as `mpirun -np P ./heat3d NX NY NZ NITER`; here
# one Python process runs per host and jax.distributed handles rendezvous.
# On a multi-host pod slice, set for each host:
#   COORD=<host0-addr:port> NPROC=<num hosts> PID=<this host's index>
# Single host (or single chip): leave them unset.
#
# Usage: scripts/run_configs.sh <1|2|3|4|5> [extra heat3d flags...]
set -euo pipefail

CONFIG=${1:?usage: run_configs.sh <1-5> [flags]}
shift || true

DIST_FLAGS=()
if [[ -n "${COORD:-}" ]]; then
  DIST_FLAGS=(--coordinator "$COORD" --num-processes "${NPROC:?}" --process-id "${PID:?}")
fi

case "$CONFIG" in
  1) # 128^3, 7-point, single rank, golden-checked (BASELINE.json config 1)
     exec python -m heat3d_tpu --grid 128 --steps 100 --mesh 1 1 1 \
       --golden-check "${DIST_FLAGS[@]}" "$@" ;;
  2) # 1024^3, 7-point, 1D slab on 8 chips (config 2)
     exec python -m heat3d_tpu --grid 1024 --steps 1000 --mesh 8 1 1 \
       "${DIST_FLAGS[@]}" "$@" ;;
  3) # 2048^3, 7-point, 3D block 2x2x2 on 8 chips (config 3)
     exec python -m heat3d_tpu --grid 2048 --steps 1000 --mesh 2 2 2 \
       "${DIST_FLAGS[@]}" "$@" ;;
  4) # 4096^3, 27-point, 3D block on 64 chips (config 4)
     exec python -m heat3d_tpu --grid 4096 --steps 500 --stencil 27pt \
       --mesh 4 4 4 "${DIST_FLAGS[@]}" "$@" ;;
  5) # 4096^3 strong-scale, bf16 stencil + fp32 residual on 128 chips (config 5)
     exec python -m heat3d_tpu --grid 4096 --steps 500 --dtype bf16 \
       --mesh 8 4 4 "${DIST_FLAGS[@]}" "$@" ;;
  *) echo "unknown config $CONFIG (want 1-5)" >&2; exit 2 ;;
esac
