"""Compare measured throughput rows against the HBM traffic-model ceilings.

Reads a bench_results.jsonl (bench.harness rows) and prints, per throughput
row, the step path it ran, its bytes/cell/update, the bandwidth ceiling at
the given HBM rate, and the achieved fraction — the "where did the rest
go" accounting BASELINE.md's traffic model sets up.

Usage: python scripts/roofline_check.py bench_results.jsonl [--hbm-gbps 819]
"""

from __future__ import annotations

import argparse
import json
import sys


def bytes_per_cell_update(row) -> tuple[float, str]:
    """Traffic model per path (BASELINE.md 'HBM traffic model')."""
    item = 2 if row["dtype"] == "bfloat16" else 4
    tb = row.get("time_blocking", 1)
    mesh = row.get("mesh", [1, 1, 1])
    single = all(m == 1 for m in mesh)
    halo = row.get("halo", "ppermute")
    overlap = row.get("overlap", False)
    # the direct kernels apply on unpadded shards for ppermute transport;
    # DMA transport and tb>2 keep the padded exchange (one extra volume
    # read+write per exchange)
    direct = halo == "ppermute" and tb in (1, 2)
    if direct and not (overlap and tb == 2):
        per_update = 2 * item / tb  # one read + one write per sweep of tb
        path = f"direct{'' if tb == 1 else '2'}{'' if single else '+faces'}"
    else:
        # exchange path: padded copy (r+w) once per exchange + sweep per
        # update (tb updates share one exchange)
        per_update = 2 * item + 2 * item / tb
        path = f"exchange(tb={tb})"
    return per_update, path


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("results")
    ap.add_argument("--hbm-gbps", type=float, default=819.0,
                    help="chip HBM bandwidth (GB/s); v5e ~819, v5p ~2765")
    args = ap.parse_args()

    rows = []
    with open(args.results) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(r, dict) and r.get("bench") == "throughput":
                rows.append(r)
    if not rows:
        print("no throughput rows found", file=sys.stderr)
        return 1

    print(f"{'grid':>6} {'dtype':>8} {'tb':>2} {'path':>16} "
          f"{'B/cell/upd':>10} {'ceiling':>9} {'measured':>9} {'achieved':>8}")
    for r in rows:
        per_update, path = bytes_per_cell_update(r)
        ceiling = args.hbm_gbps / per_update  # Gcell/s/chip
        meas = r["gcell_per_sec_per_chip"]
        grid = r["grid"][0] if len(set(r["grid"])) == 1 else "x".join(
            map(str, r["grid"]))
        flag = " (RTT!)" if r.get("rtt_dominated") else ""
        # compute dtype doesn't change HBM traffic (storage dtype does),
        # but label it so bf16-compute A/B rows are tellable apart
        if r.get("compute_dtype", "float32") != "float32":
            flag = " (c=bf16)" + flag
        print(f"{grid:>6} {r['dtype']:>8} {r.get('time_blocking', 1):>2} "
              f"{path:>16} {per_update:>10.1f} {ceiling:>9.1f} "
              f"{meas:>9.2f} {meas / ceiling:>7.1%}{flag}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
