"""Thin wrapper: the roofline row model now lives in
``heat3d_tpu/obs/perf/roofline.py`` (the ``heat3d obs roofline`` CLI),
promoted there so the analytic traffic/op-cost model and the
cost-analysis-based per-phase attribution share one module. This script
keeps the historical invocation working:

    python scripts/roofline_check.py bench_results.jsonl
        [--hbm-gbps 819] [--vpu-gops N] [--fit]

Same flags, same output (see the module docstring there for the model's
semantics and the --vpu-gops calibration rule).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heat3d_tpu.obs.perf.roofline import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
