"""Compare measured throughput rows against the HBM traffic-model ceilings
and a VPU op-cost model of the tap chain.

Reads a bench_results.jsonl (bench.harness rows) and prints, per throughput
row, the step path it ran, its bytes/cell/update, the bandwidth ceiling at
the given HBM rate, the vector-op count of the emitted tap chain (and the
VPU ceiling when ``--vpu-gops`` is given), and the achieved fraction of the
binding ceiling — the "where did the rest go" accounting BASELINE.md's
traffic model sets up.

The op count comes from :func:`heat3d_tpu.core.stencils.effective_num_taps`
driving the REAL accumulate_taps emission under the current factoring env
(HEAT3D_FACTOR_Y / HEAT3D_FACTOR_7PT) — so the printed chain cost is the
one the rows actually compiled *if* the env matches the measurement run
(each FMA term and each cached plane/row sum counts as one full-volume
vector op; kernel plane-assembly overhead is not modeled). ``--vpu-gops``
has no trustworthy public per-chip number; calibrate it from a measured
compute-bound row (e.g. 27pt tb=1: gops ≈ ops/cell x measured Gcell/s)
and then use it to sanity-check the OTHER compute-bound rows.

Usage: python scripts/roofline_check.py bench_results.jsonl
           [--hbm-gbps 819] [--vpu-gops N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bytes_per_cell_update(row) -> tuple[float, str]:
    """Traffic model per path (BASELINE.md 'HBM traffic model')."""
    item = 2 if row["dtype"] == "bfloat16" else 4
    tb = row.get("time_blocking", 1)
    mesh = row.get("mesh", [1, 1, 1])
    single = all(m == 1 for m in mesh)
    halo = row.get("halo", "ppermute")
    overlap = row.get("overlap", False)
    # the direct kernels apply on unpadded shards for ppermute transport;
    # DMA transport and tb>2 keep the padded exchange (one extra volume
    # read+write per exchange). Prefer the RESOLVED selection the harness
    # recorded (exact even for HEAT3D_NO_DIRECT A/B rows); derive for
    # legacy rows.
    if row.get("fused_dma_path"):
        # fused DMA-overlap kernels: unpadded streaming sweep, one
        # read+write per sweep of tb updates — same traffic shape as the
        # direct kernels
        return 2 * item / tb, f"fused-dma{'' if tb == 1 else '2'}"
    direct = row.get("direct_path")
    if direct is None:
        direct = halo == "ppermute" and tb in (1, 2)
    if direct and not (overlap and tb == 2):
        per_update = 2 * item / tb  # one read + one write per sweep of tb
        path = f"direct{'' if tb == 1 else '2'}{'' if single else '+faces'}"
    else:
        # exchange path: padded copy (r+w) once per exchange + sweep per
        # update (tb updates share one exchange)
        per_update = 2 * item + 2 * item / tb
        path = f"exchange(tb={tb})"
    return per_update, path


def vpu_ops_per_cell_update(row) -> int:
    """Vector ops/cell/update of the row's tap chain. Prefers the
    ``chain_ops`` the harness recorded at measurement time (exact even for
    factoring-knob A/B rows); falls back to re-deriving under the CURRENT
    factoring env for rows predating that field. Tap VALUES don't matter
    for the count, only which offsets are nonzero, so nominal
    alpha/dt/spacing are fine for the fallback."""
    if "chain_ops" in row:
        return row["chain_ops"]  # may be None: conv rows run no tap chain
    if row.get("backend") == "conv":
        return None
    from heat3d_tpu.core.stencils import chain_ops_for

    return chain_ops_for(row.get("stencil", "7pt"))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("results", nargs="+",
                    help="one or more row files (bench_results.jsonl plus "
                    "e.g. A/B rows extracted from tpu_measure.log — the "
                    "factoring A/B stages log their rows rather than "
                    "appending them to the suite record)")
    ap.add_argument("--hbm-gbps", type=float, default=819.0,
                    help="chip HBM bandwidth (GB/s); v5e ~819, v5p ~2765")
    ap.add_argument("--vpu-gops", type=float, default=None,
                    help="VPU vector throughput (Gop/s, one op = one "
                    "full-width FMA or add); calibrate from a measured "
                    "compute-bound row — no default on purpose")
    ap.add_argument("--fit", action="store_true",
                    help="per (grid, dtype, tb, path) group with >=2 "
                    "distinct chain_ops values, fit time/cell/update = "
                    "a + b*ops: linearity in ops IS the compute-bound "
                    "evidence, 1/b the marginal VPU rate, a the per-cell "
                    "fixed cost (loads/stores/plane assembly)")
    args = ap.parse_args()

    rows = []
    for results in args.results:
        with open(results) as f:
            for line in f:
                # tolerate log-style prefixes ("factor_y=0 tb=1: {...}")
                line = line.strip()
                brace = line.find("{")
                if brace < 0:
                    continue
                try:
                    r = json.loads(line[brace:])
                except json.JSONDecodeError:
                    continue
                if isinstance(r, dict) and r.get("bench") == "throughput":
                    rows.append(r)
    if not rows:
        print("no throughput rows found", file=sys.stderr)
        return 1

    print(f"{'grid':>6} {'dtype':>8} {'st':>4} {'tb':>2} {'path':>16} "
          f"{'B/cell/upd':>10} {'ops':>4} {'ceiling':>9} {'bind':>4} "
          f"{'measured':>9} {'achieved':>8}")
    for r in rows:
        per_update, path = bytes_per_cell_update(r)
        bw_ceiling = args.hbm_gbps / per_update  # Gcell/s/chip
        ops = vpu_ops_per_cell_update(r)
        ceiling, bind = bw_ceiling, "hbm"
        # ops is None for conv rows (one XLA conv op, no tap chain): the
        # VPU model doesn't apply — report against the HBM ceiling only
        if args.vpu_gops is not None and ops is not None:
            vpu_ceiling = args.vpu_gops / ops
            if vpu_ceiling < bw_ceiling:
                ceiling, bind = vpu_ceiling, "vpu"
        meas = r["gcell_per_sec_per_chip"]
        grid = r["grid"][0] if len(set(r["grid"])) == 1 else "x".join(
            map(str, r["grid"]))
        flag = " (RTT!)" if r.get("rtt_dominated") else ""
        # compute dtype doesn't change HBM traffic (storage dtype does),
        # but label it so bf16-compute A/B rows are tellable apart
        if r.get("compute_dtype", "float32") != "float32":
            flag = " (c=bf16)" + flag
        print(f"{grid:>6} {r['dtype']:>8} {r.get('stencil', '7pt'):>4} "
              f"{r.get('time_blocking', 1):>2} {path:>16} "
              f"{per_update:>10.1f} {'n/a' if ops is None else ops:>4} "
              f"{ceiling:>9.1f} {bind:>4} "
              f"{meas:>9.2f} {meas / ceiling:>7.1%}{flag}")

    if args.fit:
        _fit_op_cost(rows)
    return 0


def _fit_op_cost(rows) -> None:
    """Least-squares time/cell/update = a + b*ops over rows that differ
    ONLY in their emitted chain (same grid/dtype/tb/path). A good linear
    fit is direct evidence the kernels are compute-bound in chain ops;
    a >> b would instead indict fixed per-cell cost (assembly/shifts)."""
    from collections import defaultdict

    groups = defaultdict(list)
    for r in rows:
        if r.get("rtt_dominated"):
            continue
        _, path = bytes_per_cell_update(r)
        # compute_dtype/backend in the key: a bf16-compute A/B row has the
        # same chain_ops as its fp32-compute twin but different per-op
        # cost — pooling them would corrupt the fit silently
        key = (
            tuple(r["grid"]), r["dtype"],
            r.get("compute_dtype", "float32"), r.get("backend", "auto"),
            r.get("time_blocking", 1), path,
        )
        ops = vpu_ops_per_cell_update(r)
        if ops is None:
            continue  # conv rows: no tap chain, nothing to fit against
        ns_per_cell = 1.0 / r["gcell_per_sec_per_chip"]  # ns/cell/update
        groups[key].append((ops, ns_per_cell))
    printed = False
    for key, pts in sorted(groups.items()):
        by_ops = {}
        for ops, t in pts:
            by_ops.setdefault(ops, []).append(t)
        if len(by_ops) < 2:
            continue
        xs, ys = zip(*((o, min(ts)) for o, ts in sorted(by_ops.items())))
        n = len(xs)
        mx, my = sum(xs) / n, sum(ys) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        b = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / sxx
        a = my - b * mx
        if n >= 3:
            ss_res = sum((y - (a + b * x)) ** 2 for x, y in zip(xs, ys))
            ss_tot = sum((y - my) ** 2 for y in ys) or 1e-30
            fit_q = f"R^2={1 - ss_res / ss_tot:.3f}"
        else:
            # a line through 2 points always "fits"; don't dress that up
            fit_q = "2-point (no linearity evidence)"
        grid, dtype, cdtype, backend, tb, path = key
        cflag = "" if cdtype == "float32" else f" c={cdtype}"
        glabel = (f"{grid[0]}^3" if len(set(grid)) == 1
                  else "x".join(map(str, grid)))
        if b <= 0:
            # higher-ops rows timed FASTER: noise or a confound — that's
            # anti-evidence of compute-boundedness, not an infinite rate
            verdict = "non-positive slope — unfittable/not compute-bound"
        else:
            verdict = (
                f"marginal {1.0 / b:.0f} Gop/s, "
                f"fixed {a / (a + b * xs[0]):.0%} of the {xs[0]}-op chain"
            )
        print(
            f"\nfit {glabel} {dtype}{cflag} tb={tb} {path}: "
            f"t/cell = {a:.3f} + {b:.4f}*ops ns "
            f"({verdict}, {fit_q}, points={list(by_ops)})"
        )
        printed = True
    if not printed:
        print("\nfit: no group has >=2 distinct chain_ops values "
              "(need factoring A/B rows, e.g. HEAT3D_FACTOR_Y=0)",
              file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
