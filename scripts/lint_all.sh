#!/usr/bin/env bash
# One-shot pre-merge lint sweep (docs/ANALYSIS.md):
#
#   1. `heat3d lint --all` — every static tier in ONE process with one
#      merged verdict: the AST checkers over the source tree, the
#      IR-tier program verifier (collective topology, halo footprint,
#      dtype flow, compiled memory contract at the jaxpr level), and
#      the kernel-tier Pallas verifier (DMA discipline, ring-slot
#      races, output coverage, remote targets inside kernel bodies);
#      rc 1 only on unsuppressed error-severity findings;
#   2. ledger data lint WITH the taxonomy audit over every *ledger*.jsonl
#      argument (event names checked against the canonical registry);
#   3. provenance lint over every other .jsonl argument (bench rows).
#
# Usage: scripts/lint_all.sh [artifact.jsonl ...]
#   scripts/lint_all.sh                                   # static only
#   scripts/lint_all.sh bench_results.jsonl bench_results.ledger.jsonl
#
# Arguments are routed by name: a .jsonl containing "ledger" gets the
# ledger lint, any other .jsonl the provenance lint. Data lints here run
# UNSCOPED (no --start-line) on purpose — pre-merge, the whole artifact
# is the thing being vouched for; session-scoped linting is the bench
# suite's job. rc is nonzero if ANY stage failed, and every stage runs
# (one red lint must not hide another).
set -uo pipefail
cd "$(dirname "$0")/.."

rc=0

echo "== static + IR + kernel certification (heat3d lint --all) =="
python -m heat3d_tpu.cli lint --all || rc=1

for artifact in "$@"; do
  case "$artifact" in
    *ledger*.jsonl)
      echo "== ledger lint (--taxonomy): $artifact =="
      python scripts/check_ledger.py --taxonomy "$artifact" || rc=1
      ;;
    *.jsonl)
      echo "== provenance lint: $artifact =="
      python scripts/check_provenance.py "$artifact" || rc=1
      ;;
    *)
      echo "lint_all: skipping unrecognized artifact $artifact" >&2
      ;;
  esac
done

exit "$rc"
