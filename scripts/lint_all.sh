#!/usr/bin/env bash
# One-shot pre-merge lint sweep (docs/ANALYSIS.md):
#
#   1. `heat3d lint` — the five static checkers over the source tree
#      (rc 1 only on unsuppressed error-severity findings);
#   2. `heat3d lint --ir` — the IR-tier program verifier (traces the
#      judged config matrix and certifies collective topology, halo
#      footprint, dtype flow and the compiled memory contract at the
#      jaxpr level; same rc policy);
#   3. ledger data lint WITH the taxonomy audit over every *ledger*.jsonl
#      argument (event names checked against the canonical registry);
#   4. provenance lint over every other .jsonl argument (bench rows).
#
# Usage: scripts/lint_all.sh [artifact.jsonl ...]
#   scripts/lint_all.sh                                   # static only
#   scripts/lint_all.sh bench_results.jsonl bench_results.ledger.jsonl
#
# Arguments are routed by name: a .jsonl containing "ledger" gets the
# ledger lint, any other .jsonl the provenance lint. Data lints here run
# UNSCOPED (no --start-line) on purpose — pre-merge, the whole artifact
# is the thing being vouched for; session-scoped linting is the bench
# suite's job. rc is nonzero if ANY stage failed, and every stage runs
# (one red lint must not hide another).
set -uo pipefail
cd "$(dirname "$0")/.."

rc=0

echo "== static analysis (heat3d lint) =="
python -m heat3d_tpu.cli lint || rc=1

echo "== IR certification (heat3d lint --ir) =="
python -m heat3d_tpu.cli lint --ir || rc=1

for artifact in "$@"; do
  case "$artifact" in
    *ledger*.jsonl)
      echo "== ledger lint (--taxonomy): $artifact =="
      python scripts/check_ledger.py --taxonomy "$artifact" || rc=1
      ;;
    *.jsonl)
      echo "== provenance lint: $artifact =="
      python scripts/check_provenance.py "$artifact" || rc=1
      ;;
    *)
      echo "lint_all: skipping unrecognized artifact $artifact" >&2
      ;;
  esac
done

exit "$rc"
