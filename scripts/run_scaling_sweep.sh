#!/usr/bin/env bash
# Weak/strong-scaling sweep matrix -> JSONL + BASELINE.md efficiency tables.
#
# Emits, per (stencil, dtype): 1-chip baselines (one per distinct local
# grid), then multi-chip runs over the mesh ladder. On the pod this is the
# judged ≥90%-weak-scaling run (BASELINE.json north star); on the dev box
# the same matrix executes on the virtual 8-device CPU mesh, proving the
# plumbing end-to-end (numbers are CPU-only, not the record).
#
# Usage: [LOCAL=64] [STEPS=20] [MESHES="1 1 1;2 1 1;2 2 1;2 2 2"] \
#        [ON_CPU_MESH=1] scripts/run_scaling_sweep.sh [out.jsonl]
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-scaling_results.jsonl}"
LOCAL="${LOCAL:-64}"             # per-chip edge for weak scaling
STEPS="${STEPS:-20}"
MESHES="${MESHES:-1 1 1;2 1 1;2 2 1;2 2 2}"
STENCILS="${STENCILS:-7pt}"
DTYPES="${DTYPES:-fp32}"

max_chips=1
IFS=';' read -ra MESH_LIST <<< "$MESHES"
for m in "${MESH_LIST[@]}"; do
  read -r mx my mz <<< "$m"
  n=$((mx * my * mz))
  (( n > max_chips )) && max_chips=$n
done

RUN=(python -m heat3d_tpu.bench)
REPORT_MD="BASELINE.md"
if [[ "${ON_CPU_MESH:-}" == "1" ]]; then
  export JAX_PLATFORMS=cpu
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=$max_chips"
  unset PALLAS_AXON_POOL_IPS
  # CPU numbers must never clobber the committed TPU record
  REPORT_MD="${OUT%.jsonl}.md"
  : > "$REPORT_MD"
fi

: > "$OUT"

for stencil in $STENCILS; do
  for dtype in $DTYPES; do
    # 1-chip baselines: the weak-scaling local grid and every strong-scaling
    # global grid (G = LOCAL * mesh extent per axis).
    seen=""
    for m in "${MESH_LIST[@]}"; do
      read -r mx my mz <<< "$m"
      g="$((LOCAL * mx)) $((LOCAL * my)) $((LOCAL * mz))"
      case ";$seen;" in *";$g;"*) continue ;; esac
      seen="$seen;$g"
      "${RUN[@]}" --grid $g --mesh 1 1 1 --stencil "$stencil" \
        --dtype "$dtype" --steps "$STEPS" --bench throughput >> "$OUT"
    done
    # multi-chip runs: weak scaling (local constant) — the same rows serve
    # strong scaling wherever the global grid matches a baseline above.
    for m in "${MESH_LIST[@]}"; do
      read -r mx my mz <<< "$m"
      n=$((mx * my * mz))
      (( n == 1 )) && continue
      "${RUN[@]}" --grid $((LOCAL * mx)) $((LOCAL * my)) $((LOCAL * mz)) \
        --mesh "$mx" "$my" "$mz" --stencil "$stencil" --dtype "$dtype" \
        --steps "$STEPS" --bench throughput >> "$OUT"
    done
  done
done

python -m heat3d_tpu.bench.report "$OUT" "$REPORT_MD"
echo "sweep done -> $OUT, tables -> $REPORT_MD (meshes up to $max_chips chips)"
