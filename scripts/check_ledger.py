#!/usr/bin/env python
"""Ledger schema lint — thin wrapper over the promoted data-lint core
(heat3d_tpu.analysis.ledgerlint, re-exported through heat3d_tpu.obs.check)
so the CI gate (scripts/run_bench_suite.sh) and the operator command
(``heat3d obs check``) share one implementation.

Checks every ledger file given: required fields on every event, span
fields + monotonic span nesting, per-(run_id, proc) seq monotonicity, and
run-id consistency (each run segment opens with exactly one
``ledger_open``). rc 1 on any defect. ``--start-line N`` scopes the
report to defects at/after line N (APPEND-mode suite sessions lint only
the segments they wrote — same rule as check_provenance.py).
``--taxonomy`` additionally audits event names against the canonical
registry (heat3d_tpu/analysis/registry.py).

Usage: scripts/check_ledger.py [--taxonomy] [--start-line N] LEDGER.jsonl [...]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heat3d_tpu.obs.check import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
