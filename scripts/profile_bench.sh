#!/usr/bin/env bash
# Thin wrapper: profiling capture is now a first-class bench/solver flag
# (`--profile DIR`, obs/perf/profiling.py) that records the trace artifact
# path and the capture overhead into the run ledger. This script just
# forwards to it and summarizes the device-time breakdown (VERDICT r1
# item 2: attribute the roofline gap with a trace, not guesses).
#
# Usage: [GRID=512] [STEPS=20] [TB=1] [DTYPE=fp32] [STENCIL=7pt]
#        scripts/profile_bench.sh [outdir]
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-/tmp/heat3d_profile}"
GRID="${GRID:-512}"
STEPS="${STEPS:-20}"
TB="${TB:-1}"
DTYPE="${DTYPE:-fp32}"
STENCIL="${STENCIL:-7pt}"

rm -rf "$OUT"
python -m heat3d_tpu.bench --grid "$GRID" --steps "$STEPS" \
  --time-blocking "$TB" --dtype "$DTYPE" --stencil "$STENCIL" --mesh 1 1 1 \
  --bench throughput --profile "$OUT"

python scripts/summarize_trace.py "$OUT"
