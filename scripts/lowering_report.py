#!/usr/bin/env python
"""Compile-only evidence for the judged pod configs: what XLA inserts.

Lowers the full distributed step for each judged multi-chip config
(BASELINE.json configs 2-5) over a device-free AbstractMesh — the
single-chip dev box's substitute for a pod (SURVEY.md §4, §7.0) — and
counts the collectives in the stablehlo text: ``collective_permute``
(the halo exchange: MPI_Isend/Irecv analogue riding ICI) and
``all_reduce`` (the fp32 residual: MPI_Allreduce analogue). Writes a
markdown table (default docs/LOWERING.md) so the ICI design is a
committed, regenerable artifact rather than a claim.

Grids are scaled down (the judged GLOBAL grids don't fit one host's
tracing memory budget at fp32 x 4096^3; collective structure depends on
mesh topology + stencil + tb, not on the local block size — the real
grid only changes block shapes). The table records both the judged and
the lowered grid.

Usage: python scripts/lowering_report.py [out.md]
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from heat3d_tpu.core.config import (
    BoundaryCondition,
    GridConfig,
    MeshConfig,
    Precision,
    SolverConfig,
    StencilConfig,
)
from heat3d_tpu.parallel.step import make_step_fn, make_superstep_fn
from heat3d_tpu.parallel.topology import abstract_mesh, lower_for_mesh

# (label, judged grid, mesh, stencil, precision, tb, halo, overlap)
# — BASELINE.json configs
CONFIGS = [
    ("2: 1024^3 slab v5p-8", 1024, (8, 1, 1), "7pt", Precision.fp32(), 1,
     "ppermute", False),
    ("3: 2048^3 block v5p-8", 2048, (2, 2, 2), "7pt", Precision.fp32(), 1,
     "ppermute", False),
    ("4: 4096^3 27pt v5p-64", 4096, (4, 4, 4), "27pt", Precision.fp32(), 1,
     "ppermute", False),
    ("5: 4096^3 bf16 v5p-128", 4096, (8, 4, 4), "7pt", Precision.bf16(), 1,
     "ppermute", False),
    ("2+tb: 1024^3 slab, tb=2", 1024, (8, 1, 1), "7pt", Precision.fp32(), 2,
     "ppermute", False),
    # the fused DMA-overlap kernels: zero collective_permutes by design —
    # the halo rides kernel-initiated RDMA inside the one Mosaic custom
    # call (SURVEY §7.1 item 7); tb=2 = the fused two-update superstep
    # with the width-2 slab DMA under its phase-A sweep
    ("2+fused: 1024^3 slab, RDMA overlap", 1024, (8, 1, 1), "7pt",
     Precision.fp32(), 1, "dma", True),
    ("2+fused2: 1024^3 slab, RDMA overlap tb=2", 1024, (8, 1, 1), "7pt",
     Precision.fp32(), 2, "dma", True),
    # the 3D-block generalization (VERDICT r4 item 5): x faces ride the
    # in-kernel RDMA, y/z faces stay ppermutes seeded by the landed
    # ghosts, y/z shells patched — expected permutes = 2 per sharded
    # y/z axis, and the Mosaic call still present
    ("3+fused: 2048^3 block, RDMA-x overlap", 2048, (2, 2, 2), "7pt",
     Precision.fp32(), 1, "dma", True),
    ("5+fused: 4096^3 bf16 block, RDMA-x overlap", 4096, (8, 4, 4), "7pt",
     Precision.bf16(), 1, "dma", True),
]


def count(txt: str, op: str) -> int:
    # Lowered.as_text() spells ops with '_' or '-' depending on the JAX
    # version/pipeline (the repo's lowering tests accept both for the
    # same reason); a spelling miss here would report a false regression
    pat = op.replace("_", "[_-]")
    return len(re.findall(rf"\b{pat}\b", txt))


def lower_one(label, judged, mesh_shape, kind, prec, tb, halo, overlap):
    # small local blocks, same topology: collective structure is identical
    local = 8
    grid = tuple(local * m for m in mesh_shape)
    fused = halo == "dma" and overlap
    cfg = SolverConfig(
        grid=GridConfig(shape=grid),
        stencil=StencilConfig(kind=kind, bc=BoundaryCondition.DIRICHLET),
        mesh=MeshConfig(shape=mesh_shape),
        precision=prec,
        # portable lowering for the collective rows; the fused-DMA row
        # must dispatch the real Mosaic kernel (HEAT3D_DIRECT_FORCE below)
        backend="auto" if fused else "jnp",
        time_blocking=tb,
        halo=halo,
        overlap=overlap,
    )
    am = abstract_mesh(cfg.mesh)
    prior = os.environ.get("HEAT3D_DIRECT_FORCE")
    prior_interp = os.environ.get("HEAT3D_DIRECT_INTERPRET")
    if fused:
        os.environ["HEAT3D_DIRECT_FORCE"] = "1"
        # a stale interpret knob would override FORCE at the dispatch gate
        # and lower plain JAX ops instead of the Mosaic call
        os.environ.pop("HEAT3D_DIRECT_INTERPRET", None)
    try:
        if tb > 1:
            fn = make_superstep_fn(cfg, am)
        else:
            fn = make_step_fn(cfg, am, with_residual=True)
        dtype = jnp.dtype(prec.storage)
        txt = lower_for_mesh(
            fn, cfg.mesh, (grid, dtype, P("x", "y", "z"))
        ).as_text()
    finally:
        if fused:
            if prior is None:
                os.environ.pop("HEAT3D_DIRECT_FORCE", None)
            else:
                os.environ["HEAT3D_DIRECT_FORCE"] = prior
            if prior_interp is not None:
                os.environ["HEAT3D_DIRECT_INTERPRET"] = prior_interp
    nchips = cfg.mesh.num_devices
    sharded_axes = sum(1 for m in mesh_shape if m > 1)
    return {
        "label": label,
        "judged": f"{judged}^3",
        "lowered": "x".join(map(str, grid)),
        "mesh": "x".join(map(str, mesh_shape)),
        "chips": nchips,
        "stencil": kind,
        "dtype": str(dtype),
        "tb": tb,
        "permutes": count(txt, "collective_permute"),
        "allreduce": count(txt, "all_reduce"),
        "custom_calls": count(txt, "tpu_custom_call"),
        "sharded_axes": sharded_axes,
        # the fused-DMA routes' x halo is RDMA inside the custom call:
        # slab rows expect 0 permutes, 3D-block rows keep the 2-per-axis
        # y/z face ppermutes (seeded by the landed x ghosts); at least
        # one Mosaic call must appear either way
        "expect_permutes": (
            2 * sum(1 for m in mesh_shape[1:] if m > 1)
            if fused
            else 2 * sharded_axes
        ),
        "expect_custom_calls_min": 1 if fused else 0,
    }


def main(argv=None) -> int:
    default_out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "LOWERING.md",
    )
    out_path = (argv or sys.argv[1:] or [default_out])[0]
    rows = [lower_one(*c) for c in CONFIGS]
    lines = [
        "# Lowering report — judged pod configs, compile-only evidence",
        "",
        "Regenerate: `python scripts/lowering_report.py`. Each row lowers",
        "the FULL distributed step over a device-free AbstractMesh of the",
        "judged topology and counts the collectives XLA inserted",
        "(`collective_permute` = the ghost-cell halo exchange riding ICI —",
        "the reference's CUDA-aware MPI_Isend/Irecv; `all_reduce` = the",
        "fp32 residual — its MPI_Allreduce). Expected permute count:",
        "2 directions per SHARDED mesh axis (size-1 axes short-circuit to",
        "local wraps/BC fills), independent of grid size; tb=2 supersteps",
        "exchange width-2 ghosts in the same 2-per-axis pattern. The",
        "fused-DMA slab rows expect ZERO permutes: their halo is",
        "kernel-initiated RDMA inside the Mosaic custom call",
        "(`tpu_custom_call` >= 1). The fused 3D-block rows keep 2 permutes",
        "per sharded y/z axis — the y/z faces stay ppermutes, seeded by",
        "the RDMA-landed x ghosts (no second x transfer), with the y/z",
        "shard-boundary shells patched after the sweep.",
        "",
        "Beyond compile-only: the judged pod topologies also EXECUTE at",
        "tiny scale on virtual CPU meshes — (4,4,4) over 64 devices and",
        "(8,4,4) over 128 — bitwise-matching the undecomposed run",
        "(tests/test_multidevice.py::test_judged_pod_topology_executes).",
        "",
        "| Config | Judged grid | Lowered grid | Mesh | Chips | Stencil |"
        " Dtype | tb | collective_permute | all_reduce | tpu_custom_call |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    ok = True
    for r in rows:
        want = r["expect_permutes"]
        flag = "" if r["permutes"] == want else f" (expected {want}!)"
        ok = ok and r["permutes"] == want
        cflag = (
            "" if r["custom_calls"] >= r["expect_custom_calls_min"]
            else " (expected >= 1!)"
        )
        ok = ok and r["custom_calls"] >= r["expect_custom_calls_min"]
        lines.append(
            f"| {r['label']} | {r['judged']} | {r['lowered']} | {r['mesh']} |"
            f" {r['chips']} | {r['stencil']} | {r['dtype']} | {r['tb']} |"
            f" {r['permutes']}{flag} | {r['allreduce']} |"
            f" {r['custom_calls']}{cflag} |"
        )
    lines.append("")
    text = "\n".join(lines)
    with open(out_path, "w") as f:
        f.write(text)
    print(text)
    print(f"wrote {out_path}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
