"""Render a --dump-slice plane (.npy) to a PNG heatmap.

The visualization half of the reference class's workflow (SURVEY.md §4:
correctness by "visual/numeric inspection of dumped slices"):

    heat3d --grid 256 --steps 500 --dump-slice z 128 plane.npy
    python scripts/plot_slice.py plane.npy plane.png

Encoding choices (magnitude of a continuous scalar field): a single
perceptually-uniform sequential colormap — ``cividis``, designed for
color-vision-deficient readers; never a rainbow — with a labeled colorbar
as the legend and neutral-ink annotations.
"""

from __future__ import annotations

import os
import sys

import numpy as np


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(
            "usage: plot_slice.py plane.npy [out.png] [title]", file=sys.stderr
        )
        return 2
    src = argv[0]
    out = argv[1] if len(argv) > 1 else os.path.splitext(src)[0] + ".png"
    title = argv[2] if len(argv) > 2 else os.path.basename(src)

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    plane = np.load(src).astype(np.float64)
    fig, ax = plt.subplots(figsize=(6.4, 5.2), dpi=150)
    im = ax.imshow(
        plane.T,  # axis 0 of the plane on x, origin at the domain corner
        origin="lower",
        cmap="cividis",
        interpolation="nearest",
        aspect="equal",
    )
    cbar = fig.colorbar(im, ax=ax, shrink=0.85)
    cbar.set_label("temperature u", color="#444444")
    ax.set_title(title, color="#222222")
    ax.set_xlabel("first in-plane axis (cells)", color="#444444")
    ax.set_ylabel("second in-plane axis (cells)", color="#444444")
    ax.tick_params(colors="#666666", labelsize=8)
    for spine in ax.spines.values():
        spine.set_color("#cccccc")
    fig.tight_layout()
    fig.savefig(out)
    print(
        f"wrote {out}: {plane.shape[0]}x{plane.shape[1]} plane, "
        f"u in [{plane.min():.4g}, {plane.max():.4g}]"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
