#!/usr/bin/env bash
# Run the benchmark suite on this machine's chips and regenerate the
# measured tables in BASELINE.md (SURVEY.md §2 C9, §5 "Metrics").
#
# Usage: scripts/run_bench_suite.sh [results.jsonl]
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-bench_results.jsonl}
: > "$OUT"

# Single-chip sweep: the judged grid ladder at fp32+bf16, temporal blocking
# off/on (tb=2 = the fused one-sweep kernel, the headline setting), plus one
# overlap-split run (on one chip this isolates the split-step overhead; the
# comm-overlap benefit needs a pod). Each row emits throughput + halo p50.
# The multi-chip judged grids need a pod slice (same flags, bigger
# --grid/--mesh). Override with GRIDS/DTYPES/STEPS/TBS env vars
# (e.g. GRIDS=32 TBS=1 for a CPU smoke run).
for dtype in ${DTYPES:-fp32 bf16}; do
  for grid in ${GRIDS:-256 512 1024}; do
    for tb in ${TBS:-1 2}; do
      # a failing row (e.g. 1024^3 OOM on a small-HBM chip) skips, not aborts
      python -m heat3d_tpu.bench --grid "$grid" --steps "${STEPS:-50}" \
        --dtype "$dtype" --time-blocking "$tb" --mesh 1 1 1 \
        >> "$OUT" 2>/dev/null \
        || echo "suite: skipped grid=$grid dtype=$dtype tb=$tb (rc=$?)" >&2
    done
  done
done

if [[ -z "${SKIP_OVERLAP:-}" ]]; then
  python -m heat3d_tpu.bench --grid "${OVERLAP_GRID:-512}" \
    --steps "${STEPS:-50}" --overlap --mesh 1 1 1 --bench throughput \
    >> "$OUT" 2>/dev/null \
    || echo "suite: skipped overlap run (rc=$?)" >&2
fi

python -m heat3d_tpu.bench.report "$OUT" BASELINE.md
