#!/usr/bin/env bash
# Run the benchmark suite on this machine's chips and regenerate the
# measured tables in BASELINE.md (SURVEY.md §2 C9, §5 "Metrics").
#
# Usage: scripts/run_bench_suite.sh [results.jsonl]
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-bench_results.jsonl}
: > "$OUT"

# Single-chip sweep: sizes that fit one chip; the multi-chip judged grids
# need a pod slice (same flags, bigger --grid/--mesh). Override the sweep
# with GRIDS/DTYPES/STEPS env vars (e.g. GRIDS=32 for a CPU smoke run).
for dtype in ${DTYPES:-fp32 bf16}; do
  for grid in ${GRIDS:-256 512}; do
    python -m heat3d_tpu.bench --grid "$grid" --steps "${STEPS:-50}" \
      --dtype "$dtype" --mesh 1 1 1 >> "$OUT" 2>/dev/null
  done
done

python -m heat3d_tpu.bench.report "$OUT" BASELINE.md
