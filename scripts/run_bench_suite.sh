#!/usr/bin/env bash
# Run the benchmark suite on this machine's chips and regenerate the
# measured tables in BASELINE.md (SURVEY.md §2 C9, §5 "Metrics").
#
# Usage: scripts/run_bench_suite.sh [results.jsonl] [report.md]
# The report target defaults to BASELINE.md — the committed measured
# record. Pass a scratch path (or set REPORT_MD) for smoke/CPU runs so
# they don't clobber the on-chip tables.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${1:-bench_results.jsonl}
REPORT_MD=${2:-${REPORT_MD:-BASELINE.md}}
# One run ledger threads through every row's subprocess (the bench CLI
# activates $HEAT3D_LEDGER itself): the suite's A/B session — including
# rows replayed from a sweep journal vs freshly measured — reconstructs
# from this file alone (`heat3d obs summary`). Fresh sessions truncate it
# in lockstep with $OUT; APPEND sessions keep appending run segments.
LEDGER="${LEDGER:-${OUT%.jsonl}.ledger.jsonl}"
export HEAT3D_LEDGER="$LEDGER"
[[ -n "${APPEND:-}" ]] || : > "$LEDGER"
# ledger-lint scope: only the segments THIS session appends (same rule as
# LINT_FROM below) — a historical defect must not keep resumed sessions red
LEDGER_LINT_FROM=$(( $(wc -l < "$LEDGER" 2>/dev/null || echo 0) + 1 ))
# Row stderr lands here (NOT /dev/null): a failing row's traceback is the
# only evidence of WHY a session lost it. Fresh sessions truncate it in
# lockstep with $OUT (stale tracebacks misattribute failures); APPEND
# sessions keep it, and every session stamps a boundary header.
SUITE_LOG="${SUITE_LOG:-${OUT%.jsonl}.err.log}"
[[ -n "${APPEND:-}" ]] || : > "$SUITE_LOG"
echo "=== suite session $(date -u +%FT%TZ) (APPEND=${APPEND:-}) ===" >> "$SUITE_LOG"

# suite-level skip/fail notes go to OUR stderr (live view) AND the log —
# callers truncate stderr (tpu_measure_all tails it), the log persists
note() { echo "$*" | tee -a "$SUITE_LOG" >&2; }

# The axon pool grants its single chip to one client at a time, and a row
# SIGKILLed by its ROW_TIMEOUT leaves a stale claim that blocks the NEXT
# row's backend init until the server expires it — unguarded, one slow row
# cascades into every later row burning its whole timeout stuck in init.
# So every chip-touching step first waits (killable, claim-free) until a
# bounded probe confirms the chip answers. No-op off the axon env (CPU
# smoke runs).
wait_tpu() {
  [[ -n "${PALLAS_AXON_POOL_IPS:-}" && "${JAX_PLATFORMS:-}" != cpu ]] || return 0
  # Anchor-then-short gating (same rule + knob as tpu_measure_all.sh):
  # the first unreachable row pays the full TPU_WAIT; while the tunnel
  # stays down, later rows wait only TPU_WAIT_SHORT (default 300 s).
  # Probes run back-to-back so a heal is still caught within one
  # interval — short gates just cycle dead rows faster, and the
  # APPEND-mode driver loop retries skipped rows next attempt. A success
  # re-arms the full anchor for the next outage.
  local w="${TPU_WAIT:-1800}"
  [[ -n "${_SUITE_GATE_FAILED:-}" ]] && w="${TPU_WAIT_SHORT:-300}"
  if python -m heat3d_tpu.utils.backendprobe --wait "$w" \
      --interval "${TPU_WAIT_INTERVAL:-60}" >/dev/null 2>&1; then
    _SUITE_GATE_FAILED=""
    return 0
  fi
  _SUITE_GATE_FAILED=1
  note "suite: TPU unreachable past ${w}s; skipping: $*"
  return 1
}
# APPEND=1 resumes an interrupted measurement session instead of
# truncating the rows a prior (e.g. tunnel-wedged) run already landed;
# configs already recorded in $OUT are skipped, not re-run (no duplicate
# table rows, no re-spending the session budget on finished rows)
[[ -n "${APPEND:-}" ]] || : > "$OUT"
# provenance-lint scope: only the rows THIS session appends. Pre-existing
# rows (a resumed session's earlier attempts, or the committed legacy
# record) were some other session's responsibility — linting them here
# would keep every APPEND session permanently red.
LINT_FROM=$(( $(wc -l < "$OUT" 2>/dev/null || echo 0) + 1 ))

# has_halo GRID DTYPE -> 0 if $OUT already has the halo row for this
# exchange shape (only consulted in APPEND mode). Checked separately from
# has_row because a bench=all rung killed between its two output lines
# leaves the throughput row without its paired halo row.
has_halo() {
  [[ -n "${APPEND:-}" && -s "$OUT" ]] || return 1
  python - "$OUT" "$@" <<'EOF'
import json, sys
out, grid, dtype = sys.argv[1:4]
want_dtype = {"fp32": "float32", "bf16": "bfloat16"}[dtype]
for line in open(out):
    try:
        r = json.loads(line)
    except json.JSONDecodeError:
        continue
    if (
        r.get("bench") == "halo"
        and r.get("grid") == [int(grid)] * 3
        and r.get("dtype") == want_dtype
    ):
        sys.exit(0)
sys.exit(1)
EOF
}

# has_row STENCIL GRID DTYPE TB COMPUTE OVERLAP -> 0 if $OUT already has a
# matching throughput row (only consulted in APPEND mode)
has_row() {
  [[ -n "${APPEND:-}" && -s "$OUT" ]] || return 1
  python - "$OUT" "$@" <<'EOF'
import json, sys
out, stencil, grid, dtype, tb, compute, overlap = sys.argv[1:8]
want_dtype = {"fp32": "float32", "bf16": "bfloat16"}[dtype]
want_compute = {"fp32": "float32", "bf16": "bfloat16"}[compute]
for line in open(out):
    try:
        r = json.loads(line)
    except json.JSONDecodeError:
        continue
    if (
        r.get("bench") == "throughput"
        and r.get("stencil") == stencil
        and r.get("grid") == [int(grid)] * 3
        and r.get("dtype") == want_dtype
        and r.get("compute_dtype", "float32") == want_compute
        and r.get("time_blocking", 1) == int(tb)
        and bool(r.get("overlap")) == (overlap == "1")
    ):
        sys.exit(0)
sys.exit(1)
EOF
}
[[ -f "$REPORT_MD" ]] || : > "$REPORT_MD"

# Single-chip sweep: the judged grid ladder at fp32+bf16, temporal blocking
# off/on (tb=2 = the fused one-sweep kernel, the headline setting), plus one
# overlap-split run (on one chip this isolates the split-step overhead; the
# comm-overlap benefit needs a pod). Each row emits throughput + halo p50.
# The multi-chip judged grids need a pod slice (same flags, bigger
# --grid/--mesh). Override with GRIDS/DTYPES/STEPS/TBS env vars
# (e.g. GRIDS=32 TBS=1 for a CPU smoke run).
for stencil in ${STENCILS:-7pt 27pt}; do
  for dtype in ${DTYPES:-fp32 bf16}; do
    # judged-floor grids FIRST: a short healthy window must land the
    # 1024^3 rows (the judged metric names 1024^3-4096^3) before the
    # small-grid context rows
    for grid in ${GRIDS:-1024 512 256}; do
      for tb in ${TBS:-1 2}; do
        # the 27pt ladder is VPU-bound and dtype/tb change little; bench
        # only its judged-flavor rows (fp32 plus the bf16 tb=2 row) at
        # 512+ to keep the suite under the measurement session budget
        if [[ $stencil == 27pt ]]; then
          [[ $grid -lt 512 ]] && continue
          [[ $dtype == bf16 && $tb == 1 ]] && continue
        fi
        # halo latency depends only on (grid, dtype), not stencil/tb: emit
        # one halo row per exchange shape (--bench all on the 7pt tb=1
        # pass), throughput-only otherwise — no duplicate halo rows
        bench=throughput
        [[ $stencil == 7pt && $tb == 1 ]] && bench=all
        if has_row "$stencil" "$grid" "$dtype" "$tb" fp32 0; then
          if [[ $bench == all ]] && ! has_halo "$grid" "$dtype"; then
            # resume edge: the prior run died between the throughput line
            # and the halo line — fill in just the missing halo row
            note "suite: backfilling halo row grid=$grid dtype=$dtype"
            wait_tpu "halo backfill grid=$grid" || continue
            timeout -k 30 "${ROW_TIMEOUT:-900}" \
              python -m heat3d_tpu.bench --grid "$grid" \
              --steps "${STEPS:-50}" --dtype "$dtype" --mesh 1 1 1 \
              --bench halo >> "$OUT" 2>>"$SUITE_LOG" \
              || note "suite: halo backfill failed grid=$grid (rc=$?)"
          else
            note "suite: already recorded $stencil grid=$grid dtype=$dtype tb=$tb"
          fi
          continue
        fi
        # a failing row (e.g. 1024^3 OOM on a small-HBM chip) skips, not
        # aborts; ROW_TIMEOUT bounds a row that hangs on a wedged tunnel
        # (one stuck 1024^3 transfer must cost one row, not the stage)
        wait_tpu "$stencil grid=$grid dtype=$dtype tb=$tb" || continue
        timeout -k 30 "${ROW_TIMEOUT:-900}" \
          python -m heat3d_tpu.bench --grid "$grid" --steps "${STEPS:-50}" \
          --stencil "$stencil" --dtype "$dtype" --time-blocking "$tb" \
          --mesh 1 1 1 --bench "$bench" \
          >> "$OUT" 2>>"$SUITE_LOG" \
          || note "suite: skipped $stencil grid=$grid dtype=$dtype tb=$tb (rc=$?)"
      done
    done
  done
done

# bf16-COMPUTE A/B (judged config 5 follow-up): same bf16 storage, stencil
# math in bf16 instead of fp32 — answers whether the bf16 tb=2 ceiling gap
# is VPU-width-limited (this row speeds up) or plane-assembly-limited (it
# doesn't). Accuracy gated by tests/test_solver.py bf16-compute tier.
if [[ -z "${SKIP_BF16_COMPUTE:-}" ]]; then
  for grid in ${GRIDS:-1024 512}; do
    [[ $grid -lt 512 ]] && continue
    if has_row 7pt "$grid" bf16 2 bf16 0; then
      note "suite: already recorded bf16-compute grid=$grid"
      continue
    fi
    wait_tpu "bf16-compute grid=$grid" || continue
    timeout -k 30 "${ROW_TIMEOUT:-900}" \
      python -m heat3d_tpu.bench --grid "$grid" --steps "${STEPS:-50}" \
      --dtype bf16 --compute-dtype bf16 --time-blocking 2 --mesh 1 1 1 \
      --bench throughput >> "$OUT" 2>>"$SUITE_LOG" \
      || note "suite: skipped bf16-compute grid=$grid (rc=$?)"
  done
fi

if [[ -z "${SKIP_OVERLAP:-}" ]]; then
  if has_row 7pt "${OVERLAP_GRID:-512}" fp32 1 fp32 1; then
    note "suite: already recorded overlap run"
  elif wait_tpu "overlap run"; then
    timeout -k 30 "${ROW_TIMEOUT:-900}" \
      python -m heat3d_tpu.bench --grid "${OVERLAP_GRID:-512}" \
      --steps "${STEPS:-50}" --overlap --mesh 1 1 1 --bench throughput \
      >> "$OUT" 2>>"$SUITE_LOG" \
      || note "suite: skipped overlap run (rc=$?)"
  fi
fi

# report refuses a zero-row rewrite itself (update_baseline_md), so a
# session whose every row skipped leaves the committed tables untouched
python -m heat3d_tpu.bench.report "$OUT" "$REPORT_MD"

# Roofline attribution of the session's rows (informational: achieved
# fraction of the traffic-model ceiling per row — the "where did the rest
# go" accounting; its rc must not gate the suite, a reporting bug loses
# nothing)
python -m heat3d_tpu.obs.cli roofline "$OUT" \
  || note "suite: roofline report failed (rc=$?)"

# Lints + the perf gate LAST (after the report, so failing them never
# loses the tables): provenance — rc 1 if any row THIS SESSION wrote has
# ts null/missing, lacks its route fields, or lacks sync_rtt_s (VERDICT
# r5 weak item 2, enforced going forward); ledger — rc 1 if the session's
# event stream is schema-invalid (missing fields, broken span nesting,
# torn run-ids); regress — rc 1 if any row this session measured regressed
# past the fail band against the committed same-platform history
# (platform-aware baselines: CPU smoke rows never compare against TPU
# records — they report no_baseline and pass). Their rc is the suite's rc
# under set -e; the regress JSON verdict also lands in the suite log.
python scripts/check_provenance.py --start-line "$LINT_FROM" "$OUT"
python scripts/check_ledger.py --start-line "$LEDGER_LINT_FROM" "$LEDGER"
# Static-analysis gate (docs/ANALYSIS.md): SPMD-safety + invariant
# checkers over the source tree; rc 1 only on unsuppressed error-severity
# findings, and that rc is the suite's rc. SKIP_STATIC_LINT=1 is the
# escape hatch for sessions that must land rows while a lint fix is in
# flight (scripts/lint_all.sh still runs it pre-merge).
if [[ -z "${SKIP_STATIC_LINT:-}" ]]; then
  python -m heat3d_tpu.cli lint --json | tee -a "$SUITE_LOG"
else
  note "suite: static lint skipped (SKIP_STATIC_LINT=1)"
fi
# IR-tier certification gate (docs/ANALYSIS.md "IR tier"): trace the
# judged step/superstep/ensemble matrix in a fresh process (so the
# multi-device CPU mesh can be forced) and certify collective topology,
# halo footprint, dtype flow and the compiled memory contract at the
# jaxpr/HLO level. Same rc policy as the static lint; its rc is the
# suite's rc. SKIP_IR_LINT=1 is the escape hatch.
if [[ -z "${SKIP_IR_LINT:-}" ]]; then
  python -m heat3d_tpu.cli lint --ir --json | tee -a "$SUITE_LOG"
else
  note "suite: IR lint skipped (SKIP_IR_LINT=1)"
fi
# Kernel-tier certification gate (docs/ANALYSIS.md "Kernel tier"): trace
# every repo Pallas kernel body in a fresh process (so the multi-device
# CPU rings can be forced) and certify DMA start/wait discipline,
# ring-slot happens-before, output coverage and remote-copy neighbor
# targets — the schedules interpret-tier parity cannot see. Same rc
# policy; its rc is the suite's rc. SKIP_KERNEL_LINT=1 is the escape
# hatch.
if [[ -z "${SKIP_KERNEL_LINT:-}" ]]; then
  python -m heat3d_tpu.cli lint --kernel --json | tee -a "$SUITE_LOG"
else
  note "suite: kernel lint skipped (SKIP_KERNEL_LINT=1)"
fi
python -m heat3d_tpu.obs.cli regress "$OUT" --start-line "$LINT_FROM" \
  --json | tee -a "$SUITE_LOG"

# SLO + timeline smoke (informational, AFTER the regress gate): evaluate
# the session ledger against the configured objectives ($HEAT3D_SLO_SPEC,
# else the built-in generous defaults — the path stays exercised either
# way) and export the session's Chrome-trace timeline next to the rows.
# Both fail SOFT (a breach on a smoke ledger is a note, not a gate);
# SKIP_SLO_SMOKE=1 skips. docs/OBSERVABILITY.md §7.
if [[ -z "${SKIP_SLO_SMOKE:-}" ]]; then
  python -m heat3d_tpu.obs.cli slo "$LEDGER" --json | tee -a "$SUITE_LOG" \
    || note "suite: slo smoke verdict nonzero (rc=$?) — informational"
  python -m heat3d_tpu.obs.cli timeline "$LEDGER" \
    -o "${OUT%.jsonl}.trace.json" >> "$SUITE_LOG" 2>&1 \
    || note "suite: timeline export failed (rc=$?) — informational"
else
  note "suite: slo/timeline smoke skipped (SKIP_SLO_SMOKE=1)"
fi

# Autotune smoke + cache-schema lint (informational, AFTER the gates so
# their rc still decides the suite): a budgeted `tune run` over the FULL
# extended time_blocking lattice (1..4 — deep tb included, so the
# search-measure-decide-cache loop AND the deep-tb validity pruning stay
# alive end to end) — on CPU its numbers are smoke, not record, so it
# writes a session-local store (never the operator's ~/.cache default)
# and both steps fail SOFT. SKIP_TUNE_SMOKE=1 skips; docs/TUNING.md.
if [[ -z "${SKIP_TUNE_SMOKE:-}" ]]; then
  TUNE_CACHE="${TUNE_CACHE:-${OUT%.jsonl}.tune_cache.json}"
  python -m heat3d_tpu.cli tune run --grid "${TUNE_GRID:-24}" \
    --steps "${TUNE_STEPS:-8}" --repeats 1 --probe-steps 4 \
    --budget-s "${TUNE_BUDGET_S:-45}" --knob time_blocking=1,2,3,4 \
    --cache "$TUNE_CACHE" --json >> "$SUITE_LOG" 2>&1 \
    || note "suite: tune smoke failed (rc=$?) — informational"
  python -m heat3d_tpu.cli tune lint --cache "$TUNE_CACHE" \
    >> "$SUITE_LOG" 2>&1 \
    || note "suite: tune cache-schema lint failed (rc=$?) — informational"
fi

# Exchange-plan A/B smoke (informational, beside the tune smoke): one
# tiny monolithic vs partitioned throughput pair through the persistent
# exchange plans (parallel/plan.py), judged by the tune/decide pairwise
# logic (scripts/ab_decide.py = thin wrapper) — keeps the plan knob's
# measure-decide loop alive end to end between chip sessions. On CPU the
# verdict is smoke, not record (docs/TUNING.md "Persistent exchange
# plans"; the pod A/B is POD_RUNBOOK stage 3-plan). Fails SOFT;
# SKIP_PLAN_SMOKE=1 skips.
if [[ -z "${SKIP_PLAN_SMOKE:-}" ]]; then
  PLAN_LOG="${OUT%.jsonl}.plan_ab.log"
  : > "$PLAN_LOG"
  for hp in monolithic partitioned; do
    if wait_tpu "plan smoke $hp"; then
      timeout -k 30 "${ROW_TIMEOUT:-900}" \
        python -m heat3d_tpu.bench --grid "${PLAN_GRID:-24}" \
        --steps "${PLAN_STEPS:-8}" --bench throughput --halo-plan "$hp" \
        2>>"$SUITE_LOG" | sed "s/^/halo_plan=$hp: /" >> "$PLAN_LOG" \
        || note "suite: plan smoke $hp failed (rc=$?) — informational"
    fi
  done
  python scripts/ab_decide.py "$PLAN_LOG" >> "$SUITE_LOG" 2>&1 \
    || note "suite: plan A/B decide failed (rc=$?) — informational"
fi

# Fused in-kernel RDMA interpret-parity smoke (informational, beside
# the plan smoke): the REAL fused-RDMA superstep kernel (interpret
# tier, 4-device CPU ring) must stay BITWISE-equal to the certified
# fused-DMA kernel bodies it shares its sweep with — tb=1 and tb=2,
# both BCs, monolithic AND genuine-sub-block partitioned plans — with
# a machine-checked JSON verdict. Catches a fused-route value drift
# between chip sessions without needing a TPU (the throughput A/B is
# POD_RUNBOOK stage 3-fused). Fails SOFT; SKIP_FUSED_SMOKE=1 skips.
if [[ -z "${SKIP_FUSED_SMOKE:-}" ]]; then
  timeout -k 30 "${ROW_TIMEOUT:-900}" python - <<'PYEOF' \
    || note "suite: fused RDMA parity smoke failed (rc=$?) — informational"
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from heat3d_tpu.core.config import BoundaryCondition, GridConfig, MeshConfig
from heat3d_tpu.core.stencils import STENCILS, stencil_taps
from heat3d_tpu.parallel.plan import build_plan
from heat3d_tpu.utils.compat import shard_map
import heat3d_tpu.ops.stencil_dma_fused as dma_mod
import heat3d_tpu.ops.stencil_fused_rdma as rdma_mod

grid = (16, 16, 16)
gc = GridConfig(shape=grid)
taps = stencil_taps(STENCILS["7pt"], gc.alpha, gc.effective_dt(), gc.spacing)
u = jnp.asarray(np.random.default_rng(7).random(grid, np.float32))
mesh = Mesh(np.array(jax.devices()).reshape(4), ("x",))
spec = P("x")
ud = jax.device_put(u, NamedSharding(mesh, spec))

def run(fn, **kw):
    return np.asarray(
        jax.jit(shard_map(lambda x: fn(x, taps, **kw), mesh=mesh,
                          in_specs=spec, out_specs=spec, check_vma=False))(ud)
    )

cases, ok = [], True
for periodic in (False, True):
    bc = BoundaryCondition.PERIODIC if periodic else BoundaryCondition.DIRICHLET
    for tb, dma_fn, rdma_fn in (
        (1, dma_mod.apply_step_fused_dma, rdma_mod.apply_step_fused_rdma),
        (2, dma_mod.apply_superstep_fused_dma,
         rdma_mod.apply_superstep_fused_rdma),
    ):
        kw = dict(axis_name="x", axis_size=4, mesh_axes=("x",),
                  periodic=periodic, bc_value=1.5, interpret=True)
        base = run(dma_fn, **kw)
        for mode in ("monolithic", "partitioned"):
            plan = build_plan(MeshConfig(shape=(4, 1, 1)), bc, width=tb,
                              transport="ppermute", mode=mode,
                              min_part_bytes=0)
            got = run(rdma_fn, plan=plan, **kw)
            bitwise = bool(np.array_equal(got, base))
            ok &= bitwise
            cases.append({"tb": tb, "periodic": periodic, "plan": mode,
                          "bitwise": bitwise,
                          "max_abs_diff": float(np.max(np.abs(got - base)))})
print(json.dumps({"fused_smoke": {"ok": ok, "cases": cases}}))
sys.exit(0 if ok else 1)
PYEOF
else
  note "suite: fused RDMA parity smoke skipped (SKIP_FUSED_SMOKE=1)"
fi

# Serve smoke (informational, beside the tune smoke): the built-in tiny
# multi-bucket batch through the batched scenario engine — submit ->
# shape-bucketed packing -> streamed results, CPU-safe and sub-minute —
# so the serving path (docs/SERVING.md) can't rot between serving
# sessions. Fails SOFT; SKIP_SERVE_SMOKE=1 skips.
if [[ -z "${SKIP_SERVE_SMOKE:-}" ]]; then
  python -m heat3d_tpu.cli serve --smoke >> "$SUITE_LOG" 2>&1 \
    || note "suite: serve smoke failed (rc=$?) — informational"
else
  note "suite: serve smoke skipped (SKIP_SERVE_SMOKE=1)"
fi

# Async-engine smoke + AOT cold/warm A/B (informational, beside the
# serve smoke; docs/SERVING.md "Async engine & cold start"): the same
# tiny multi-bucket batch through the always-on engine, run TWICE
# against one fresh session-local AOT store — the first run measures the
# compile stall and exports the executables, the second must load them
# back (aot.hits > 0, compile_stall_s == 0: the cold-start-elimination
# contract, machine-checked from the two --verdict JSON lines printed to
# the console). Also a budgeted engine-bucket `tune run --batch-members`
# so the b2^k batch-bucket entries the engine resolves through stay
# exercised (docs/TUNING.md). Fails SOFT; SKIP_ASYNC_SMOKE=1 skips.
if [[ -z "${SKIP_ASYNC_SMOKE:-}" ]]; then
  # always a suite-derived scratch path (never an operator override):
  # the A/B needs a guaranteed-cold store, and rm -rf on a caller-
  # supplied directory would delete a real accumulated AOT cache
  AOT_DIR="${OUT%.jsonl}.aot_cache"
  rm -rf "$AOT_DIR"
  ASYNC_COLD=$(HEAT3D_AOT_CACHE="$AOT_DIR" \
    python -m heat3d_tpu.cli serve --async --smoke --verdict \
    2>>"$SUITE_LOG" | tail -n 1) \
    || note "suite: async serve smoke (cold) failed (rc=$?) — informational"
  ASYNC_WARM=$(HEAT3D_AOT_CACHE="$AOT_DIR" \
    python -m heat3d_tpu.cli serve --async --smoke --verdict \
    2>>"$SUITE_LOG" | tail -n 1) \
    || note "suite: async serve smoke (warm) failed (rc=$?) — informational"
  echo "suite: async smoke cold verdict: $ASYNC_COLD"
  echo "suite: async smoke warm verdict: $ASYNC_WARM"
  python - "$ASYNC_COLD" "$ASYNC_WARM" <<'PYEOF' \
    || note "suite: AOT cold/warm A/B verdict failed — informational"
import json, sys
cold = json.loads(sys.argv[1])["serve_verdict"]
warm = json.loads(sys.argv[2])["serve_verdict"]
ca, wa = cold["engine"]["aot"], warm["engine"]["aot"]
ok = (cold["ok"] and warm["ok"] and wa["hits"] > 0
      and wa["compile_stall_s"] == 0)
print(json.dumps({"aot_cold_warm_ab": {
    "ok": ok,
    "cold_compile_stall_s": round(ca["compile_stall_s"], 3),
    "warm_hits": wa["hits"], "warm_load_s": round(wa["load_s"], 4),
    "warm_compile_stall_s": wa["compile_stall_s"]}}))
sys.exit(0 if ok else 1)
PYEOF
  TUNE_CACHE="${TUNE_CACHE:-${OUT%.jsonl}.tune_cache.json}"
  python -m heat3d_tpu.cli tune run --grid "${TUNE_GRID:-16}" \
    --batch-members 4 --steps 6 --repeats 1 --probe-steps 0 \
    --budget-s "${TUNE_BUDGET_S:-45}" --knob time_blocking=1,2 \
    --cache "$TUNE_CACHE" --json >> "$SUITE_LOG" 2>&1 \
    || note "suite: engine-bucket tune smoke failed (rc=$?) — informational"
else
  note "suite: async serve smoke skipped (SKIP_ASYNC_SMOKE=1)"
fi

# Equation-frontend smoke (informational, beside the serve smoke): one
# spec-built family end-to-end through the solver CLI with the fp64
# golden check — the declarative eqn subsystem (docs/EQUATIONS.md) can't
# rot between equation sessions. Sub-minute on CPU. Fails SOFT;
# SKIP_EQN_SMOKE=1 skips.
if [[ -z "${SKIP_EQN_SMOKE:-}" ]]; then
  python -m heat3d_tpu.cli --grid 24 --steps 5 \
    --equation advection-diffusion --golden-check >> "$SUITE_LOG" 2>&1 \
    || note "suite: eqn smoke failed (rc=$?) — informational"
else
  note "suite: eqn smoke skipped (SKIP_EQN_SMOKE=1)"
fi

# Time-integrator smoke (informational, beside the eqn smoke;
# docs/INTEGRATORS.md): the two non-default integrator families
# end-to-end through the solver CLI on a forced 4-device CPU mesh — a
# leapfrog wave run (the two-level (u, u_prev) carry through the
# sharded superstep) and an implicit-cg run at 10x the explicit CFL
# bound (dt 5/3 vs the 1/6 forward-Euler limit at unit spacing), whose
# cg_solve ledger event must record a converged solve (iterations
# within the HEAT3D_CG_MAX_ITERS cap, relative residual at tolerance)
# — the stiff-dt convergence contract, machine-checked. Always CPU
# (the path under test is the integrator plumbing, not the chip),
# sub-minute. Fails SOFT; SKIP_TIMEINT_SMOKE=1 skips.
if [[ -z "${SKIP_TIMEINT_SMOKE:-}" ]]; then
  TI_LED="${OUT%.jsonl}.timeint.ledger.jsonl"
  : > "$TI_LED"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    timeout -k 30 "${ROW_TIMEOUT:-900}" \
    python -m heat3d_tpu.cli --grid 16 --steps 6 --mesh 4 1 1 \
    --backend jnp --equation wave --integrator leapfrog \
    >> "$SUITE_LOG" 2>&1 \
    || note "suite: leapfrog wave smoke failed (rc=$?) — informational"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    HEAT3D_LEDGER="$TI_LED" \
    timeout -k 30 "${ROW_TIMEOUT:-900}" \
    python -m heat3d_tpu.cli --grid 16 --steps 4 --mesh 4 1 1 \
    --backend jnp --integrator implicit-cg --dt 1.6666667 \
    >> "$SUITE_LOG" 2>&1 \
    || note "suite: implicit-cg smoke run failed (rc=$?) — informational"
  python - "$TI_LED" <<'PYEOF' \
    || note "suite: timeint smoke verdict failed — informational"
import json, sys
evs = []
try:
    with open(sys.argv[1]) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    evs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
except OSError:
    pass
cg = [e for e in evs if e.get("event") == "cg_solve"]
# the run's LAST solve is the audited one (a warmup call may log a
# zero-step event first)
last = cg[-1] if cg else {}
ok = (
    len(cg) >= 1
    and 1 <= last.get("cg_iters", 0) <= 64
    and 0.0 <= last.get("cg_relres", 1.0) <= 1e-5
)
print(json.dumps({"timeint_smoke": {
    "ok": ok, "cg_solves": len(cg),
    "cg_iters": last.get("cg_iters"),
    "cg_relres": last.get("cg_relres"),
}}))
sys.exit(0 if ok else 1)
PYEOF
else
  note "suite: timeint smoke skipped (SKIP_TIMEINT_SMOKE=1)"
fi

# Elastic-heal smoke (informational, beside the other smokes;
# docs/RESILIENCE.md "Elastic degradation"): a supervised run on a forced
# 4-device CPU mesh loses 2 devices mid-run (injected partial-device-loss)
# under --heal-mode elastic, must re-factorize onto the survivors and
# COMPLETE without operator action — machine-checked from the ledger
# (elastic_refactor + degraded_mode_enter + supervised_end at the target
# step) with the JSON verdict on the console. Always CPU (the path under
# test is the re-plan, not the chip), sub-minute. Fails SOFT;
# SKIP_ELASTIC_SMOKE=1 skips.
if [[ -z "${SKIP_ELASTIC_SMOKE:-}" ]]; then
  ELASTIC_LED="${OUT%.jsonl}.elastic.ledger.jsonl"
  ELASTIC_CK="${OUT%.jsonl}.elastic_ck"
  : > "$ELASTIC_LED"
  rm -rf "$ELASTIC_CK"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    HEAT3D_FAULTS="partial-device-loss:step=4:keep=2" \
    HEAT3D_LEDGER="$ELASTIC_LED" \
    timeout -k 30 "${ROW_TIMEOUT:-900}" \
    python -m heat3d_tpu.cli --grid 8 --steps 8 --mesh 4 1 1 \
    --backend jnp --checkpoint "$ELASTIC_CK" --checkpoint-every 2 \
    --supervise --heal-mode elastic >> "$SUITE_LOG" 2>&1 \
    || note "suite: elastic smoke run failed (rc=$?) — informational"
  python - "$ELASTIC_LED" <<'PYEOF' \
    || note "suite: elastic smoke verdict failed — informational"
import json, sys
evs = []
try:
    with open(sys.argv[1]) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    evs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
except OSError:
    pass
ref = [e for e in evs if e.get("event") == "elastic_refactor"]
ent = [e for e in evs if e.get("event") == "degraded_mode_enter"]
end = [e for e in evs if e.get("event") == "supervised_end"]
ok = (
    len(ref) >= 1 and len(ent) >= 1 and len(end) >= 1
    and end[-1].get("steps_done") == 8
    and ref[-1].get("new_mesh") == [2, 1, 1]
)
print(json.dumps({"elastic_smoke": {
    "ok": ok,
    "refactors": len(ref),
    "new_mesh": ref[-1].get("new_mesh") if ref else None,
    "restitch_s": ref[-1].get("restitch_s") if ref else None,
    "steps_done": end[-1].get("steps_done") if end else None,
    "degraded": end[-1].get("degraded") if end else None,
}}))
sys.exit(0 if ok else 1)
PYEOF
else
  note "suite: elastic smoke skipped (SKIP_ELASTIC_SMOKE=1)"
fi

# Sustained-soak smoke (informational; docs/SERVING.md "Load, overload &
# soak"): a seeded ~60s open-loop soak on a forced 4-device CPU mesh with
# a partial device loss injected mid-run — per-stream admission, fair
# packing, the pre-warm ladder, the requeue path and the machine-checked
# verdict all exercised in one bounded pass. The soak_smoke JSON line
# carries the conservation law (admitted + shed == submitted via the
# verdict's ok), the degraded window, and the zero-post-warmup-compile-
# stall criterion. Always CPU (the path under test is overload control,
# not the chip). Fails SOFT; SKIP_SOAK_SMOKE=1 skips.
if [[ -z "${SKIP_SOAK_SMOKE:-}" ]]; then
  SOAK_MIX="${OUT%.jsonl}.soak_mix.json"
  SOAK_AOT="${OUT%.jsonl}.soak_aot"
  rm -rf "$SOAK_AOT"
  cat > "$SOAK_MIX" <<'JSONEOF'
{
  "duration_s": 60,
  "seed": 42,
  "ramp": {"kind": "diurnal", "period_s": 30, "min_frac": 0.5},
  "engine": {"max_batch": 2, "max_per_stream": 4, "workers": 1},
  "streams": [
    {"name": "tenant-a", "rate_hz": 2.0,
     "scenarios": [
       {"grid": 16, "steps": 4, "alpha": 0.5, "seed": 1,
        "mesh": [4, 1, 1]},
       {"grid": 16, "steps": 3, "alpha": 0.8, "init": "gaussian",
        "seed": 2, "mesh": [4, 1, 1]}
     ]},
    {"name": "flood", "rate_hz": 4.0,
     "burst": {"every_s": 10, "len_s": 3, "multiplier": 5},
     "scenarios": [
       {"grid": 24, "steps": 20, "alpha": 0.3, "seed": 3,
        "mesh": [4, 1, 1]}
     ]}
  ]
}
JSONEOF
  SOAK_LINE=$(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    HEAT3D_FAULTS="partial-device-loss:after=20:keep=2" \
    HEAT3D_AOT_CACHE="$SOAK_AOT" \
    timeout -k 30 "${ROW_TIMEOUT:-900}" \
    python -m heat3d_tpu.cli serve --loadgen "$SOAK_MIX" \
    --duration "${SOAK_DURATION:-60}" --verdict \
    2>>"$SUITE_LOG" | tail -n 1) \
    || note "suite: soak smoke run failed (rc=$?) — informational"
  python - "$SOAK_LINE" <<'PYEOF' \
    || note "suite: soak smoke verdict failed — informational"
import json, sys
try:
    v = json.loads(sys.argv[1])["soak_verdict"]
except Exception:
    print(json.dumps({"soak_smoke": {"ok": False, "error": "no verdict"}}))
    sys.exit(1)
ok = bool(v.get("ok")) and v.get("slo") == "pass"
print(json.dumps({"soak_smoke": {
    "ok": ok, "arrivals": v.get("arrivals"),
    "submitted": v.get("submitted"), "admitted": v.get("admitted"),
    "shed": v.get("shed"), "requeues": v.get("requeues"),
    "degraded_s": v.get("degraded_s"),
    "compile_stall_after_warmup": v.get("compile_stall_after_warmup"),
    "sustained_member_gcell_per_s": v.get("sustained_member_gcell_per_s"),
    "slo": v.get("slo")}}))
sys.exit(0 if ok else 1)
PYEOF
else
  note "suite: soak smoke skipped (SKIP_SOAK_SMOKE=1)"
fi

# Monitored-soak smoke (informational; docs/OBSERVABILITY.md §8): the
# live SLO burn-rate leg, forced to alert — an impossible latency
# ceiling under --monitor --abort-on-burn must terminate the replay
# early (rc 1) with >=1 slo_burn_alert plus monitor_start /
# monitor_summary in the ledger and a machine-readable partial verdict
# (aborted == true). Proves the alerting path end-to-end the way the
# elastic smoke proves the failover path: by firing it. Always CPU.
# Fails SOFT; SKIP_MONITOR_SMOKE=1 skips.
if [[ -z "${SKIP_MONITOR_SMOKE:-}" ]]; then
  MON_MIX="${OUT%.jsonl}.monitor_mix.json"
  MON_LEDGER="${OUT%.jsonl}.monitor_ledger.jsonl"
  rm -f "$MON_LEDGER"
  cat > "$MON_MIX" <<'JSONEOF'
{
  "duration_s": 30,
  "seed": 7,
  "rate_hz": 3.0,
  "engine": {"max_batch": 2, "workers": 1},
  "monitor": {"interval_s": 0.25, "fast_window_s": 2, "slow_window_s": 4},
  "slo": {"objectives": [
    {"name": "impossible-p50", "kind": "serve_latency",
     "percentile": 50, "max_s": 0.000001}
  ]},
  "streams": [
    {"name": "tenant-a", "weight": 1,
     "scenarios": [{"grid": 12, "steps": 3, "alpha": 0.5, "seed": 1}]}
  ]
}
JSONEOF
  MON_RC=0
  MON_LINE=$(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    timeout -k 30 "${ROW_TIMEOUT:-900}" \
    python -m heat3d_tpu.cli serve --loadgen "$MON_MIX" \
    --monitor --abort-on-burn --verdict --ledger "$MON_LEDGER" \
    2>>"$SUITE_LOG" | tail -n 1) || MON_RC=$?
  # rc 1 is the EXPECTED outcome here (the soak is built to be aborted)
  [[ "$MON_RC" -eq 1 ]] \
    || note "suite: monitor smoke rc=$MON_RC (expected 1) — informational"
  python - "$MON_LINE" "$MON_LEDGER" <<'PYEOF' \
    || note "suite: monitor smoke verdict failed — informational"
import json, sys
try:
    v = json.loads(sys.argv[1])["soak_verdict"]
except Exception:
    print(json.dumps({"monitor_smoke": {"ok": False, "error": "no verdict"}}))
    sys.exit(1)
alerts = opens = summaries = 0
try:
    with open(sys.argv[2]) as f:
        for line in f:
            try:
                name = json.loads(line).get("event")
            except Exception:
                continue
            alerts += name == "slo_burn_alert"
            opens += name == "monitor_start"
            summaries += name == "monitor_summary"
except OSError:
    pass
mon = v.get("monitor") or {}
ok = (
    bool(v.get("aborted"))
    and v.get("abort_reason") == "slo_burn"
    and not v.get("ok")
    and alerts >= 1 and opens == 1 and summaries == 1
    and mon.get("alerts", 0) >= 1
)
print(json.dumps({"monitor_smoke": {
    "ok": ok, "aborted": v.get("aborted"), "partial": v.get("partial"),
    "alerts_in_ledger": alerts, "monitor": mon}}))
sys.exit(0 if ok else 1)
PYEOF
else
  note "suite: monitor smoke skipped (SKIP_MONITOR_SMOKE=1)"
fi

# Comm-probe smoke (informational; docs/OBSERVABILITY.md §9): the
# per-link halo probe on a forced 4-device CPU mesh — both x-axis links
# (lo, hi) must land comm_probe ledger events carrying plan-predicted
# bytes joined to a positive measured time, machine-checked with the
# JSON verdict on the console. Always CPU (the path under test is the
# probe plumbing, not the interconnect). Sub-minute. Fails SOFT;
# SKIP_COMM_SMOKE=1 skips.
if [[ -z "${SKIP_COMM_SMOKE:-}" ]]; then
  COMM_LED="${OUT%.jsonl}.comm.ledger.jsonl"
  : > "$COMM_LED"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    HEAT3D_COMM_PROBE_ITERS=3 \
    timeout -k 30 "${ROW_TIMEOUT:-900}" \
    python -m heat3d_tpu.obs.comm.probe --grid 16 --mesh 4 1 1 \
    --json --ledger "$COMM_LED" >> "$SUITE_LOG" 2>&1 \
    || note "suite: comm probe smoke failed (rc=$?) — informational"
  python - "$COMM_LED" <<'PYEOF' \
    || note "suite: comm probe verdict failed — informational"
import json, sys
rows = []
try:
    with open(sys.argv[1]) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if e.get("event") == "comm_probe":
                    rows.append(e)
except OSError:
    pass
links = sorted({(e.get("axis_name"), e.get("direction")) for e in rows})
ok = (
    links == [("x", "hi"), ("x", "lo")]
    and all(e.get("bytes_predicted", 0) > 0 for e in rows)
    and all(e.get("t_s", 0) > 0 for e in rows)
)
print(json.dumps({"comm_smoke": {
    "ok": ok, "rows": len(rows),
    "links": [".".join(l) for l in links],
    "gbps": [round(e.get("gbps", 0), 4) for e in rows],
}}))
sys.exit(0 if ok else 1)
PYEOF
else
  note "suite: comm probe smoke skipped (SKIP_COMM_SMOKE=1)"
fi
