#!/usr/bin/env bash
# THE pod question the fused DMA-overlap kernels exist to answer
# (docs/POD_RUNBOOK.md §3): fused RDMA-under-the-sweep vs
# faces-direct-over-ppermute, at tb=1 and the headline tb=2, on an x-slab
# mesh. One command on a pod slice; single-host multi-chip works too.
#
# Usage: scripts/pod_ab_fused.sh [results.log]
# Env: MESH (default "8 1 1" — the fused route's x-slab scope; an
#      x-sharded BLOCK mesh like "2 2 2" exercises the 3D route instead:
#      RDMA-x under the sweep + y/z face ppermutes + shell patches, tb=1
#      arms only — tb=2 on a block mesh falls back with a config error,
#      which the log line records as "(no row: ...)", expected),
#      GRIDS (default "512 1024"), STEPS (default 50), ROW_TIMEOUT (s),
#      plus the usual multi-host flags via HEAT3D_BENCH_ARGS (e.g.
#      "--coordinator host0:9999 --num-processes 2 --process-id $K").
#
# Output: ab_decide-parseable lines "fused=<0|1> tb=<1|2> grid=<G>: {row}"
# appended to the log; finish with `python scripts/ab_decide.py <log>`
# (pairs differing only in the `fused` knob decide the route).
set -uo pipefail
cd "$(dirname "$0")/.."

LOG="${1:-pod_ab_fused.log}"
MESH="${MESH:-8 1 1}"
# slab = axes 1/2 unsharded; block meshes run the 3D route, whose fused
# scope is tb=1 only (the tb=2 superstep keeps faces-direct there)
read -r _mx _my _mz <<<"$MESH"
SLAB=$([[ "${_my:-1}" == 1 && "${_mz:-1}" == 1 ]] && echo 1 || echo 0)
echo "=== pod_ab_fused $(date -u +%FT%TZ) mesh=$MESH slab=$SLAB ===" | tee -a "$LOG"

for grid in ${GRIDS:-512 1024}; do
  for tb in 1 2; do
    for fused in 0 1; do
      if [[ $fused == 1 && $tb == 2 && $SLAB == 0 ]]; then
        echo "fused=1 tb=2 grid=$grid: skipped (block mesh: fused tb=2 out of scope)" \
          | tee -a "$LOG"
        continue
      fi
      args=(--grid "$grid" --steps "${STEPS:-50}" --mesh $MESH
            --time-blocking "$tb" --bench throughput
            ${HEAT3D_BENCH_ARGS:-})
      # fused arm: RDMA inside the sweep kernel; control arm: the
      # faces-direct step (bulk kernel + faces over async ppermutes —
      # the default route, overlap implicit in its data independence)
      [[ $fused == 1 ]] && args+=(--halo dma --overlap)
      err=$(mktemp)
      out=$(timeout -k 30 "${ROW_TIMEOUT:-1200}" \
        python -m heat3d_tpu.bench "${args[@]}" 2>"$err" | tail -1)
      rc=$?
      if [[ -z $out ]]; then
        # a lost arm must say why (off-TPU fused arm, OOM, wedge), not
        # log an empty line ab_decide silently skips
        out="(no row: rc=$rc — $(tail -1 "$err" | cut -c1-160))"
      fi
      rm -f "$err"
      echo "fused=$fused tb=$tb grid=$grid: $out" | tee -a "$LOG"
    done
  done
  # the judged halo p50 on real ICI rides along once per grid
  err=$(mktemp)
  out=$(timeout -k 30 "${ROW_TIMEOUT:-1200}" \
    python -m heat3d_tpu.bench --grid "$grid" --mesh $MESH --bench halo \
    ${HEAT3D_BENCH_ARGS:-} 2>"$err" | tail -1)
  rc=$?
  [[ -z $out ]] && out="(no row: rc=$rc — $(tail -1 "$err" | cut -c1-160))"
  rm -f "$err"
  echo "halo grid=$grid: $out" | tee -a "$LOG"
done

echo "--- decisions" | tee -a "$LOG"
python scripts/ab_decide.py "$LOG" 2>&1 | tee -a "$LOG" || true
echo "=== done $(date -u +%FT%TZ) ===" | tee -a "$LOG"
