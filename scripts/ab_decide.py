#!/usr/bin/env python
"""Thin wrapper: the pairing/decision logic now lives in
``heat3d_tpu/tune/decide.py``, promoted there so the autotuner's search
driver (``heat3d tune run``) and this measurement-log workflow share one
implementation (the same promotion pattern as scripts/roofline_check.py).
This script keeps the historical invocation working:

    python scripts/ab_decide.py tpu_measure.log [more.log ...]
        [--all-sessions] [--min-win PCT]

Same flags, same output (see the module docstring there for session
scoping and the --min-win threshold semantics).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heat3d_tpu.tune.decide import (  # noqa: E402,F401 - re-exported API
    METRIC_KEYS,
    SESSION_HEADERS,
    decide,
    main,
    pair_rows,
    parse_knobs,
    parse_lines,
)

if __name__ == "__main__":
    sys.exit(main())
